"""Training-dynamics observatory: on-device per-layer parameter/gradient
health time-series.

The reference framework's `show_parameter_stats_period` prints per-parameter
value/grad/momentum magnitudes every N batches by syncing each tensor to the
host. On TPU that per-param round-trip is exactly the sync stall the jitted
step exists to avoid, so this module computes the whole table as **one fused
on-device reduction appended to the traced step**: `plan()` resolves each
trainable parameter's grad var and optimizer moments at trace time,
`sampled_stats()` emits a single [groups, fields] float32 array inside the
jit (gated by `lax.cond` on the step counter so off-period steps pay one
predicate, not the reduction), and the executor ships it back in the normal
fetch round-trip — the same transfer that already carries fetches, so no
extra syncs.

Three layers:

1. **On-device** — per-series {weight l2/rms/max-abs, grad l2/rms/zero-frac,
   update ratio sqrt(sum dW^2)/(||W||+eps), optimizer-moment rms}. Series are
   per-parameter on small programs and collapse to planner roles
   (parallel.planner.classify_params: embedding/attn_qkv/ffn_up/...) past
   MAX_PARAM_SERIES, bounding cardinality on billion-param programs. Fields
   with nothing to measure (no grad writer, no moments, no update this step)
   carry the -1.0 absent sentinel; NaN therefore always means genuinely
   non-finite values.
2. **History + verdicts** — a bounded ring per series with EWMA baselines,
   classifying each sample into the stable codes of HEALTH_CATALOG
   (dead-layer / frozen-param / exploding-update / saturating / ...). The
   grad-status half of the catalog is shared with inspector.GradientAudit,
   which delegates to `classify_grad()` so the two planes can never disagree
   on what "vanishing" means. Samples also stream to a JSONL file next to
   the telemetry step log (PADDLE_TPU_DYNAMICS_LOG overrides).
3. **Surfacing** — dynamics_* gauges (sentinel.ALERT_CATALOG pages on
   update-ratio spikes and dead layers), the /dynamics obs-server endpoint,
   `python -m paddle_tpu dynamics` CLI, and a crash-report section.

Knobs: PADDLE_TPU_DYNAMICS=0 disables; PADDLE_TPU_DYNAMICS_PERIOD (default
16) sets the sampling period; both read per-plan so tests/bench can flip
them via override(). The eager fallback path does not sample — dynamics
rides the traced step only.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry
from .framework.desc import VarType
from .framework.framework import grad_var_name

STATE_KEY = "__dynamics__"

STAT_FIELDS = (
    "weight_l2", "weight_rms", "weight_max_abs",
    "grad_l2", "grad_rms", "grad_zero_frac",
    "update_ratio", "moment_rms",
)

# fields that may legitimately be absent (-1.0 on device -> None on host)
_OPTIONAL_FIELDS = frozenset(
    ("grad_l2", "grad_rms", "grad_zero_frac", "update_ratio", "moment_rms"))

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")

# Single constants table for every band in the observatory — inspector's
# GradientAudit defaults resolve from here too (satellite: the two
# subsystems can never disagree on what "vanishing" means).
THRESHOLDS: Dict[str, float] = {
    # per-step grad classification (shared with GradientAudit)
    "grad_vanishing_abs_mean": 1e-8,
    "grad_exploding_max_abs": 1e3,
    # time-series verdicts
    "dead_grad_rms": 1e-12,          # grad present but ~exactly zero
    "frozen_update_ratio": 1e-12,    # weights not moving despite live grads
    "exploding_update_floor": 1e-1,  # |dW|/|W| above this is always suspect
    "exploding_update_band": 8.0,    # ... or this multiple of the EWMA
    "saturating_fraction": 0.995,    # activation |mean| vs max-abs
    # window lengths (samples, not steps)
    "verdict_window": 8,
    "verdict_warmup": 2,
}

# Stable health codes. Every classification site goes through _code() so
# tools/check_registry.py can pin this catalog against the emit sites in
# both directions (a code emitted but not cataloged, or cataloged but never
# emitted, fails the lint).
HEALTH_CATALOG: Dict[str, str] = {
    "ok": "series within all bands",
    "dead-layer": "grad rms ~ 0 across the verdict window (no learning "
                  "signal reaches this layer)",
    "frozen-param": "update ratio ~ 0 across the verdict window while "
                    "grads are live (optimizer not applying them)",
    "exploding-update": "|dW|/|W| above the absolute floor or the EWMA "
                        "band (LR spike / divergence precursor)",
    "saturating": "activation |mean| pinned against max-abs (probe sites; "
                  "nonlinearity stuck in its flat region)",
    "nonfinite": "NaN/Inf values in the gradient",
    "zero": "gradient identically zero this step (or param detached)",
    "vanishing": "gradient |mean| below the vanishing band",
    "exploding": "gradient max-abs above the exploding band",
}

MAX_PARAM_SERIES = 32      # past this, series collapse to planner roles
RING_CAPACITY = 512        # samples kept per series
EWMA_ALPHA = 0.15          # matches sentinel.Baseline smoothing
DEFAULT_PERIOD = 16
_EPS = 1e-12


def _code(code: str) -> str:
    assert code in HEALTH_CATALOG, f"uncataloged health code {code!r}"
    return code


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------

_FORCE_ENABLED: Optional[bool] = None
_FORCE_PERIOD: Optional[int] = None


def enabled() -> bool:
    if _FORCE_ENABLED is not None:
        return _FORCE_ENABLED
    return os.environ.get("PADDLE_TPU_DYNAMICS", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def period() -> int:
    if _FORCE_PERIOD is not None:
        return _FORCE_PERIOD
    raw = os.environ.get("PADDLE_TPU_DYNAMICS_PERIOD", "").strip()
    try:
        p = int(raw) if raw else DEFAULT_PERIOD
    except ValueError:
        p = DEFAULT_PERIOD
    return max(p, 1)


class override:
    """Context manager forcing the observatory on/off (and optionally the
    period) regardless of the environment — the bench A/B arms and the
    parity test use this rather than mutating os.environ."""

    def __init__(self, enabled: Optional[bool], period: Optional[int] = None):
        self._enabled = enabled
        self._period = period
        self._saved: Tuple[Optional[bool], Optional[int]] = (None, None)

    def __enter__(self):
        global _FORCE_ENABLED, _FORCE_PERIOD
        self._saved = (_FORCE_ENABLED, _FORCE_PERIOD)
        _FORCE_ENABLED = self._enabled
        if self._period is not None:
            _FORCE_PERIOD = int(self._period)
        return self

    def __exit__(self, *exc):
        global _FORCE_ENABLED, _FORCE_PERIOD
        _FORCE_ENABLED, _FORCE_PERIOD = self._saved
        return False


def cache_token(program) -> Optional[Tuple[bool, int]]:
    """Part of the executor's jit-cache key: flipping the knob or the
    period must recompile (the traced step's outputs change shape)."""
    if not enabled() or plan(program) is None:
        return None
    return (True, period())


# ---------------------------------------------------------------------------
# Trace-time plan
# ---------------------------------------------------------------------------

class _ParamEntry:
    __slots__ = ("name", "grad", "sparse_grad", "moments", "role")

    def __init__(self, name, grad, sparse_grad, moments, role):
        self.name = name
        self.grad = grad
        self.sparse_grad = sparse_grad
        self.moments = moments
        self.role = role


class _Group:
    __slots__ = ("name", "role", "params")

    def __init__(self, name, role, params):
        self.name = name
        self.role = role
        self.params = params


class DynamicsPlan:
    __slots__ = ("groups", "grab_names", "period", "n_params")

    def __init__(self, groups, grab_names, period_, n_params):
        self.groups = groups
        self.grab_names = grab_names
        self.period = period_
        self.n_params = n_params


def _param_roles(program, params) -> Dict[str, str]:
    try:
        from .parallel.planner import classify_params
        roles = classify_params(program)
    except Exception:
        roles = {}
    return {p: roles.get(p, "dense") for p in params}


def _discover_moments(block, param_shapes) -> Dict[str, List[str]]:
    """Optimizer accumulators: inputs of any op with a Param slot whose
    persistable desc shape equals the param's (excludes the [1]-shaped
    global beta-pow accumulators)."""
    moments: Dict[str, List[str]] = {}
    for op in block.ops:
        pnames = op.desc.inputs.get("Param")
        if not pnames or pnames[0] not in param_shapes:
            continue
        pname = pnames[0]
        pshape = param_shapes[pname]
        for slot, names in op.desc.inputs.items():
            if slot in ("Param", "Grad", "LearningRate"):
                continue
            for n in names:
                if n == pname or not block.desc.has_var(n):
                    continue
                d = block.desc.var(n)
                if not d.persistable or d.shape is None:
                    continue
                if tuple(d.shape) != tuple(pshape):
                    continue
                if (d.dtype or "float32") not in _FLOAT_DTYPES:
                    continue
                bucket = moments.setdefault(pname, [])
                if n not in bucket:
                    bucket.append(n)
    return moments


def _build_plan(program) -> Optional[DynamicsPlan]:
    block = program.global_block()
    params = [p for p in block.all_parameters()
              if getattr(p, "trainable", True)
              and (p.dtype or "float32") in _FLOAT_DTYPES]
    if not params:
        return None

    written = set()
    for op in block.ops:
        written.update(op.output_arg_names)

    entries = []
    for p in params:
        g = grad_var_name(p.name)
        grad = None
        sparse = False
        if g in written and block.desc.has_var(g):
            d = block.desc.var(g)
            if (d.dtype or "float32") in _FLOAT_DTYPES:
                grad = g
                sparse = d.type == VarType.SELECTED_ROWS
        entries.append((p.name, grad, sparse, tuple(p.shape or ())))
    if not any(e[1] for e in entries):
        # no grads written anywhere: startup / serving / inference program
        return None

    param_shapes = {name: shape for name, _, _, shape in entries}
    moments = _discover_moments(block, param_shapes)
    roles = _param_roles(program, list(param_shapes))

    pents = [_ParamEntry(name, grad, sparse,
                         tuple(moments.get(name, ())), roles[name])
             for name, grad, sparse, _ in entries]

    if len(pents) <= MAX_PARAM_SERIES:
        groups = [_Group(e.name, e.role, [e]) for e in pents]
    else:
        by_role: Dict[str, List[_ParamEntry]] = {}
        for e in pents:
            by_role.setdefault(e.role, []).append(e)
        groups = [_Group(role, role, es)
                  for role, es in sorted(by_role.items())]
    groups.sort(key=lambda grp: grp.name)

    grab = sorted({e.grad for e in pents if e.grad is not None})
    return DynamicsPlan(groups, tuple(grab), period(), len(pents))


def plan(program) -> Optional[DynamicsPlan]:
    """Resolve (and cache on the program) the reduction plan, or None when
    dynamics is off / the program trains nothing / it is an inspector
    bisection clone."""
    if not enabled():
        return None
    if getattr(program, "_inspector_internal", False):
        return None
    key = (getattr(program, "_version", 0), period())
    cached = getattr(program, "_dynamics_plan", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    built = _build_plan(program)
    program._dynamics_plan = (key, built)
    return built


# ---------------------------------------------------------------------------
# On-device fused reduction (traced inside the executor's step fn)
# ---------------------------------------------------------------------------

def _group_row(grp: _Group, old_state, new_state, grabs):
    import jax.numpy as jnp
    f32 = jnp.float32
    zero = jnp.zeros((), f32)
    w_sumsq, w_max, w_n = zero, zero, 0.0
    g_sumsq, g_nonzero, g_n = zero, zero, 0.0
    d_sumsq = zero
    m_sumsq, m_n = zero, 0.0
    has_grad = has_update = has_moment = False

    for ent in grp.params:
        w_old = old_state.get(ent.name)
        if w_old is None:
            continue
        w_new = new_state.get(ent.name, w_old)
        gval = grabs.get(ent.grad) if ent.grad is not None else None
        # sparse-grad params: EVERY statistic (weight, update, moment)
        # reduces over the rows this step touched — a full-table pass
        # would reintroduce the O(table rows) temporaries the sparse
        # apply path exists to avoid (pinned by test_sparse_grad's
        # temp_bytes_independent_of_table_rows). SelectedRows-ness is a
        # RUNTIME value type (the var desc still says LOD_TENSOR), so
        # the traced value's `.rows`, not the plan, is the signal
        rows = getattr(gval, "rows", None)
        if rows is not None:
            wf = jnp.take(jnp.asarray(w_new), rows, axis=0).astype(f32)
            of = jnp.take(jnp.asarray(w_old), rows, axis=0).astype(f32)
        else:
            wf = jnp.asarray(w_new).astype(f32)
            of = jnp.asarray(w_old).astype(f32)
        w_sumsq = w_sumsq + jnp.sum(jnp.square(wf))
        w_max = jnp.maximum(w_max, jnp.max(jnp.abs(wf)))
        w_n += float(wf.size)
        if ent.name in new_state:
            has_update = True
            d_sumsq = d_sumsq + jnp.sum(jnp.square(wf - of))
        if gval is not None:
            # SelectedRows grads reduce over the touched rows only — no
            # densify (the sparse_densify_fallback counters stay at 0)
            gf = jnp.asarray(getattr(gval, "values", gval)).astype(f32)
            has_grad = True
            g_sumsq = g_sumsq + jnp.sum(jnp.square(gf))
            g_nonzero = g_nonzero + jnp.sum((gf != 0).astype(f32))
            g_n += float(gf.size)
        for mname in ent.moments:
            mval = new_state.get(mname, old_state.get(mname))
            if mval is None:
                continue
            mval = jnp.asarray(mval)
            if rows is not None and mval.shape == jnp.shape(w_new):
                mval = jnp.take(mval, rows, axis=0)
            mf = mval.astype(f32)
            has_moment = True
            m_sumsq = m_sumsq + jnp.sum(jnp.square(mf))
            m_n += float(mf.size)

    absent = jnp.asarray(-1.0, f32)
    w_l2 = jnp.sqrt(w_sumsq)
    row = [
        w_l2,
        jnp.sqrt(w_sumsq / max(w_n, 1.0)),
        w_max,
        jnp.sqrt(g_sumsq) if has_grad else absent,
        jnp.sqrt(g_sumsq / max(g_n, 1.0)) if has_grad else absent,
        (1.0 - g_nonzero / max(g_n, 1.0)) if has_grad else absent,
        (jnp.sqrt(d_sumsq) / (w_l2 + _EPS)) if has_update else absent,
        jnp.sqrt(m_sumsq / max(m_n, 1.0)) if has_moment else absent,
    ]
    return jnp.stack([jnp.asarray(v, f32) for v in row])


def sampled_stats(dyn_plan: Optional[DynamicsPlan], old_state, new_state,
                  grabs, rng_counter):
    """[len(groups), len(STAT_FIELDS)] float32, or None when no plan. Off
    period-boundary steps return a NaN filler (never read host-side — the
    executor knows the counter — but it must be popped before check_nan)."""
    if dyn_plan is None:
        return None
    import jax
    import jax.numpy as jnp
    shape = (len(dyn_plan.groups), len(STAT_FIELDS))

    def _take(_):
        return jnp.stack([_group_row(grp, old_state, new_state, grabs)
                          for grp in dyn_plan.groups])

    def _skip(_):
        return jnp.full(shape, jnp.nan, jnp.float32)

    if dyn_plan.period <= 1:
        return _take(None)
    hit = jnp.mod(jnp.asarray(rng_counter, jnp.uint32),
                  jnp.uint32(dyn_plan.period)) == 0
    return jax.lax.cond(hit, _take, _skip, None)


# ---------------------------------------------------------------------------
# Per-step grad classification (shared with inspector.GradientAudit)
# ---------------------------------------------------------------------------

def classify_grad(nonfinite: bool, l2: float, abs_mean: float,
                  max_abs: float,
                  vanishing_threshold: Optional[float] = None,
                  exploding_threshold: Optional[float] = None) -> str:
    """The one grad-health decision procedure: GradientAudit delegates here
    so its verdicts and the observatory's use identical bands."""
    vt = (THRESHOLDS["grad_vanishing_abs_mean"]
          if vanishing_threshold is None else vanishing_threshold)
    et = (THRESHOLDS["grad_exploding_max_abs"]
          if exploding_threshold is None else exploding_threshold)
    if nonfinite:
        return _code("nonfinite")
    if l2 == 0.0:
        return _code("zero")
    if abs_mean < vt:
        return _code("vanishing")
    if max_abs > et:
        return _code("exploding")
    return _code("ok")


# ---------------------------------------------------------------------------
# Host-side observatory: rings, EWMA baselines, verdicts, export
# ---------------------------------------------------------------------------

class _Series:
    __slots__ = ("role", "ring", "ewma", "n", "code", "since_step")

    def __init__(self, role: str):
        self.role = role
        self.ring = collections.deque(maxlen=RING_CAPACITY)
        self.ewma: Dict[str, float] = {}
        self.n = 0
        self.code = _code("ok")
        self.since_step: Optional[int] = None


class _Observatory:
    def __init__(self):
        self.lock = threading.RLock()
        self.programs: Dict[str, Dict[str, _Series]] = {}
        self.activations: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self.samples = 0
        self._log_fh = None
        self._log_path: Optional[str] = None

    # -- JSONL export -------------------------------------------------------

    def _resolve_log_path(self) -> Optional[str]:
        explicit = os.environ.get("PADDLE_TPU_DYNAMICS_LOG", "").strip()
        if explicit:
            return explicit
        step_log = telemetry.step_log_path()
        if step_log:
            root, _ = os.path.splitext(step_log)
            return root + ".dynamics.jsonl"
        return None

    def _write_log(self, rec: Dict[str, Any]):
        path = self._resolve_log_path()
        if path is None:
            return
        try:
            if self._log_fh is None or self._log_path != path:
                if self._log_fh is not None:
                    self._log_fh.close()
                self._log_fh = open(path, "a", buffering=1)
                self._log_path = path
            self._log_fh.write(json.dumps(rec) + "\n")
        except OSError:
            self._log_fh = None
            self._log_path = None

    # -- classification -----------------------------------------------------

    def _classify(self, s: _Series, vals: Dict[str, Optional[float]]) -> str:
        present = [v for v in vals.values() if v is not None]
        if any(not math.isfinite(v) for v in present):
            return _code("nonfinite")
        win = int(THRESHOLDS["verdict_window"])
        hist = [h[1] for h in list(s.ring)[-(win - 1):]] + [vals]
        g = vals.get("grad_rms")
        u = vals.get("update_ratio")
        if g is not None and len(hist) >= win and all(
                h.get("grad_rms") is not None
                and h["grad_rms"] <= THRESHOLDS["dead_grad_rms"]
                for h in hist):
            return _code("dead-layer")
        if (u is not None and g is not None
                and g > THRESHOLDS["dead_grad_rms"]
                and len(hist) >= win and all(
                    h.get("update_ratio") is not None
                    and h["update_ratio"]
                    <= THRESHOLDS["frozen_update_ratio"]
                    for h in hist)):
            return _code("frozen-param")
        base = s.ewma.get("update_ratio")
        if (u is not None and base is not None
                and s.n >= THRESHOLDS["verdict_warmup"]
                and u > max(THRESHOLDS["exploding_update_floor"],
                            THRESHOLDS["exploding_update_band"] * base)):
            return _code("exploding-update")
        return _code("ok")

    # -- sample intake ------------------------------------------------------

    def record(self, prog_label: str, step: int, dyn_plan: DynamicsPlan,
               row_arr: np.ndarray):
        arr = np.asarray(row_arr, np.float64)
        log_recs = []
        with self.lock:
            series_map = self.programs.setdefault(prog_label, {})
            ts = time.time()
            for gi, grp in enumerate(dyn_plan.groups):
                vals: Dict[str, Optional[float]] = {}
                for fi, fname in enumerate(STAT_FIELDS):
                    v = float(arr[gi, fi])
                    if fname in _OPTIONAL_FIELDS and v < 0.0:
                        vals[fname] = None
                    else:
                        vals[fname] = v
                s = series_map.get(grp.name)
                if s is None:
                    s = series_map[grp.name] = _Series(grp.role)
                code = self._classify(s, vals)
                if code != s.code:
                    s.since_step = step
                s.code = code
                # score-before-absorb: the sample was judged against the
                # baseline it did not yet influence
                for fname, v in vals.items():
                    if v is None or not math.isfinite(v):
                        continue
                    prev = s.ewma.get(fname)
                    s.ewma[fname] = (v if prev is None else
                                     prev + EWMA_ALPHA * (v - prev))
                s.ring.append((step, vals))
                s.n += 1
                self._emit_series_gauges(prog_label, grp.name, vals)
                log_recs.append({
                    "ts": ts, "program": prog_label, "step": step,
                    "series": grp.name, "role": grp.role, "code": code,
                    **{k: (v if v is None or math.isfinite(v) else str(v))
                       for k, v in vals.items()}})
            self.samples += 1
            self._emit_program_gauges(prog_label, series_map)
        # JSONL export happens outside the observatory lock (file IO can
        # block); each record is one buffered write, so lines from
        # concurrent recorders interleave whole, never torn
        for rec in log_recs:
            self._write_log(rec)

    def _emit_series_gauges(self, prog_label, series, vals):
        u = vals.get("update_ratio")
        if u is not None and math.isfinite(u):
            telemetry.gauge(
                "dynamics_update_ratio",
                "per-series |dW|/(|W|+eps) from the fused on-device "
                "dynamics reduction",
                labels=("program", "series")).labels(
                    program=prog_label, series=series).set(u)
        g = vals.get("grad_rms")
        if g is not None and math.isfinite(g):
            telemetry.gauge(
                "dynamics_grad_rms",
                "per-series gradient RMS (dynamics observatory)",
                labels=("program", "series")).labels(
                    program=prog_label, series=series).set(g)
        w = vals.get("weight_rms")
        if w is not None and math.isfinite(w):
            telemetry.gauge(
                "dynamics_weight_rms",
                "per-series parameter RMS (dynamics observatory)",
                labels=("program", "series")).labels(
                    program=prog_label, series=series).set(w)

    def _emit_program_gauges(self, prog_label, series_map):
        dead = sum(1 for s in series_map.values()
                   if s.code == "dead-layer")
        frozen = sum(1 for s in series_map.values()
                     if s.code == "frozen-param")
        unhealthy = sum(1 for s in series_map.values() if s.code != "ok")
        # emitted every sample (including 0) so the sentinel baselines warm
        # up on healthy history instead of skipping an absent series
        telemetry.gauge(
            "dynamics_dead_layers",
            "series currently classified dead-layer",
            labels=("program",)).labels(program=prog_label).set(dead)
        telemetry.gauge(
            "dynamics_frozen_params",
            "series currently classified frozen-param",
            labels=("program",)).labels(program=prog_label).set(frozen)
        telemetry.gauge(
            "dynamics_unhealthy_series",
            "series with any non-ok dynamics verdict",
            labels=("program",)).labels(program=prog_label).set(unhealthy)
        telemetry.counter(
            "dynamics_samples_total",
            "dynamics samples recorded by the observatory",
            labels=("program",)).labels(program=prog_label).inc()

    # -- activation saturation (fed from inspector probes) ------------------

    def observe_probes(self, prog_label: str, stats):
        with self.lock:
            acts = self.activations.setdefault(prog_label, {})
            for site, st in stats.items():
                if getattr(site, "kind", None) != "probe":
                    continue
                try:
                    mx = max(abs(st.min), abs(st.max))
                    sat = (mx > 0 and st.size > 1 and st.abs_mean
                           >= THRESHOLDS["saturating_fraction"] * mx)
                    acts[site.var] = {
                        "code": _code("saturating") if sat else _code("ok"),
                        "abs_mean": st.abs_mean, "max_abs": mx,
                        "op_index": site.op_index}
                except Exception:
                    continue

    # -- read side ----------------------------------------------------------

    def verdicts(self) -> List[Dict[str, Any]]:
        out = []
        with self.lock:
            for prog, series_map in self.programs.items():
                for name, s in series_map.items():
                    if s.code != "ok":
                        out.append({"program": prog, "series": name,
                                    "role": s.role, "code": s.code,
                                    "since_step": s.since_step})
            for prog, acts in self.activations.items():
                for var, rec in acts.items():
                    if rec.get("code") != "ok":
                        out.append({"program": prog, "series": var,
                                    "role": "activation",
                                    "code": rec["code"],
                                    "since_step": None})
        return out

    def payload(self, recent: int = 32) -> Dict[str, Any]:
        with self.lock:
            programs = {}
            for prog, series_map in self.programs.items():
                series = {}
                for name, s in series_map.items():
                    rows = list(s.ring)[-max(recent, 0):]
                    last = rows[-1][1] if rows else {}
                    series[name] = {
                        "role": s.role, "verdict": s.code,
                        "since_step": s.since_step, "samples": s.n,
                        "baseline": dict(s.ewma), "last": last,
                        "recent": [{"step": st, **vals}
                                   for st, vals in rows]}
                programs[prog] = {
                    "series": series,
                    "activations": self.activations.get(prog, {})}
            return {"enabled": enabled(), "period": period(),
                    "fields": list(STAT_FIELDS),
                    "thresholds": dict(THRESHOLDS),
                    "health_codes": dict(HEALTH_CATALOG),
                    "samples_recorded": self.samples,
                    "programs": programs,
                    "verdicts": self.verdicts()}

    def crash_section(self) -> Optional[Dict[str, Any]]:
        with self.lock:
            if not self.programs and not self.activations:
                return None
            last = {}
            for prog, series_map in self.programs.items():
                last[prog] = {
                    name: {"verdict": s.code,
                           "last": (s.ring[-1][1] if s.ring else {}),
                           "step": (s.ring[-1][0] if s.ring else None)}
                    for name, s in series_map.items()}
            samples = self.samples
        return {"verdicts": self.verdicts(), "last": last,
                "samples_recorded": samples}


_OBS = _Observatory()


# ---------------------------------------------------------------------------
# Executor entry points
# ---------------------------------------------------------------------------

def on_step(program, prog_label: str, stats, rng_counter: int):
    """Record the per-step stats array if this step was a sample (the
    executor passes the pre-increment counter the traced cond saw)."""
    dyn_plan = plan(program)
    if dyn_plan is None or stats is None:
        return
    if int(rng_counter) % dyn_plan.period != 0:
        return
    try:
        _OBS.record(prog_label, int(rng_counter), dyn_plan,
                    np.asarray(stats))
    except Exception:
        pass


def on_window(program, prog_label: str, stats, base_counter: int,
              steps: int):
    """Record the period-boundary rows out of a run_steps window's stacked
    [K, groups, fields] stats (step i ran with counter base_counter+i)."""
    dyn_plan = plan(program)
    if dyn_plan is None or stats is None:
        return
    try:
        arr = np.asarray(stats)
        for i in range(int(steps)):
            c = int(base_counter) + i
            if c % dyn_plan.period == 0:
                _OBS.record(prog_label, c, dyn_plan, arr[i])
    except Exception:
        pass


def observe_probes(prog_label: str, stats):
    """Inspector hook: activation-probe stats feed `saturating` verdicts."""
    if not enabled():
        return
    try:
        _OBS.observe_probes(prog_label, stats)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Read side
# ---------------------------------------------------------------------------

def payload(recent: int = 32) -> Dict[str, Any]:
    """The /dynamics endpoint + `dynamics --json` body."""
    return _OBS.payload(recent=recent)


def verdicts() -> List[Dict[str, Any]]:
    return _OBS.verdicts()


def crash_section() -> Optional[Dict[str, Any]]:
    """Compact last-snapshot for inspector crash/hang reports."""
    return _OBS.crash_section()


def reset():
    """Drop all recorded history (tests)."""
    global _OBS
    with _OBS.lock:
        if _OBS._log_fh is not None:
            try:
                _OBS._log_fh.close()
            except OSError:
                pass
    _OBS = _Observatory()
