"""Weighted running averages (reference: python/paddle/fluid/average.py).
Pure-Python aggregation helpers — they never touch the Program."""

from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number(v):
    return isinstance(v, (int, float)) or (
        isinstance(v, np.ndarray) and v.shape == (1,))


def _is_number_or_matrix(v):
    return _is_number(v) or isinstance(v, np.ndarray)


class WeightedAverage:
    """sum(value_i * weight_i) / sum(weight_i) (reference average.py:35)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError(
                "The 'value' must be a number(int, float) or a numpy "
                "ndarray.")
        if not _is_number(weight):
            raise ValueError("The 'weight' must be a number(int, float).")
        if self.numerator is None or self.denominator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator is None:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage.")
        if self.denominator == 0:
            raise ValueError("The denominator is zero.")
        return self.numerator / self.denominator
