"""Run sentinel: statistical anomaly detection + hang forensics (ISSUE 17).

The observability plane (telemetry / tracing / obs_server) measures; this
module *watches* the measurements and judges them, three layers:

1. **Anomaly detection** — every rule in `ALERT_CATALOG` names one
   telemetry metric and is scored against a rolling statistical baseline
   (`Baseline`): an EWMA of the mean plus a MAD-derived deviation scale
   over a bounded window of recent samples, warmup-gated so the first few
   samples can never alert. A sample whose z-score breaches the rule's
   threshold in the rule's bad direction raises an alert into a
   deduplicated ledger: a repeat of the same rule within its cooldown
   increments the existing entry's count instead of re-alerting, so one
   incident is one ledger row no matter how many samples it spans. Each
   *new* ledger entry increments `sentinel_alerts_total{rule,severity}`
   and records a `log_event("alert", ...)`.

2. **Hang forensics** — the executor arms a watchdog around every
   `Executor.run`/`run_steps` dispatch (`arm_dispatch`/`disarm_dispatch`).
   The deadline is max(60 s, 20x the rolling step time), overridable with
   `PADDLE_TPU_SENTINEL_HANG_S`. On expiry the watchdog dumps every
   thread's stack (`sys._current_frames`), the recent span ring and the
   flight-recorder tail plus a telemetry snapshot into a hang report in
   the inspector crash-report format (kind="hang" — `python -m paddle_tpu
   inspect` renders it), and flips `/healthz` to 503 with reason=hang.
   When the stalled dispatch finally returns, `disarm` clears the hang
   state — the process reports recovered without a restart.

3. **Surfacing** — `/alerts` on obs_server.py, alert/hang state folded
   into `/healthz` and `/report`, per-host alert counts on
   `fleet.local_snapshot()` so straggler verdicts can name the alerting
   host, and the `python -m paddle_tpu sentinel` CLI (`--smoke` injects a
   stall plus a loss spike and prints the ledger).

Enable with `PADDLE_TPU_SENTINEL=1` (picked up at import via
`maybe_start_from_env`) or programmatically with `sentinel.start()`.
`tools/check_registry.py check_alert_rules` lints ALERT_CATALOG against
telemetry.METRIC_CATALOG both ways, the same discipline as
check_metric_names.
"""

from __future__ import annotations

import itertools
import os
import statistics
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from . import telemetry

DEFAULT_INTERVAL_S = 5.0     # live-poll cadence over the telemetry registry
DEFAULT_WATCH_TICK_S = 0.2   # watchdog deadline-check cadence
DEFAULT_WARMUP = 8           # baseline samples before a rule may fire
DEFAULT_COOLDOWN_S = 60.0
HANG_FLOOR_S = 60.0
HANG_MULTIPLIER = 20.0       # x rolling step time (matches /healthz staleness)
_LEDGER_CAP = 256
_SPAN_TAIL = 200             # spans carried into a hang report

SEVERITIES = ("warn", "page")
DIRECTIONS = ("high", "low")
REDUCERS = ("max", "min", "mean")


def _rule(metric, direction, z=4.0, severity="warn",
          cooldown_s=DEFAULT_COOLDOWN_S, reduce="max", label_filter=None,
          min_value=None, warmup=DEFAULT_WARMUP, help=""):
    return {"metric": metric, "direction": direction, "z": float(z),
            "severity": severity, "cooldown_s": float(cooldown_s),
            "reduce": reduce, "label_filter": label_filter,
            "min_value": min_value, "warmup": int(warmup), "help": help}


# The declarative rule catalog: rule name -> (metric, bad direction,
# z-threshold, severity, cooldown). Every metric must exist in
# telemetry.METRIC_CATALOG with a label set the rule's filter/reduce can
# consume — check_alert_rules pins it. `min_value` additionally gates the
# alert on an absolute level, so a statistically-huge z over a tiny
# baseline (SLO burn going 0.0 -> 0.3) stays quiet.
ALERT_CATALOG = {
    "step_time_regression": _rule(
        "executor_last_step_seconds", "high", z=4.0, severity="warn",
        help="step wall time jumped above its rolling baseline"),
    "loss_spike": _rule(
        "train_loss", "high", z=4.0, severity="page", reduce="max",
        help="training loss spiked above its rolling baseline"),
    "grad_norm_spike": _rule(
        "grad_l2", "high", z=4.0, severity="warn", reduce="max",
        help="a per-param gradient L2 (inspector gauge) spiked"),
    "duty_cycle_drop": _rule(
        "device_duty_cycle", "low", z=4.0, severity="warn",
        help="device busy fraction fell below its rolling baseline"),
    "emb_cache_hit_drop": _rule(
        "emb_cache_hit_rate", "low", z=4.0, severity="warn", reduce="min",
        help="an embedding table's cache hit rate collapsed"),
    "slo_fast_burn": _rule(
        "slo_burn_rate", "high", z=3.0, severity="page", reduce="max",
        label_filter={"window": "fast"}, min_value=1.0,
        help="a model's fast-window error-budget burn exceeded 1.0"),
    # training-dynamics observatory (dynamics.py)
    "dynamics_update_ratio_spike": _rule(
        "dynamics_update_ratio", "high", z=4.0, severity="page",
        reduce="max",
        help="a series' |dW|/|W| update ratio spiked above its rolling "
             "baseline (LR spike / divergence precursor)"),
    "dynamics_dead_layer": _rule(
        "dynamics_dead_layers", "high", z=4.0, severity="page",
        reduce="max", min_value=1,
        help="the observatory classified one or more series dead-layer "
             "(grad rms ~ 0 across the verdict window)"),
    "dynamics_frozen_param": _rule(
        "dynamics_frozen_params", "high", z=4.0, severity="warn",
        reduce="max", min_value=1,
        help="the observatory classified one or more series frozen-param "
             "(live grads, zero update ratio)"),
}


class Baseline:
    """EWMA mean + MAD deviation over a bounded window of recent samples.

    `score(x)` is the z-score of x against the baseline *before* x is
    absorbed; None until `warmup` samples have been seen. The deviation
    scale is the window's MAD scaled to normal-consistency (1.4826x),
    floored at 5% of |mean| so a perfectly flat series doesn't turn every
    wiggle into an infinite z — a flat baseline alerts on a >~20% move at
    z=4, not on the first least-significant-bit flip."""

    REL_FLOOR = 0.05

    def __init__(self, alpha: float = 0.15, window: int = 128,
                 warmup: int = DEFAULT_WARMUP):
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.values: deque = deque(maxlen=int(window))
        self.mean: Optional[float] = None
        self.n = 0

    def scale(self) -> Optional[float]:
        if not self.values:
            return None
        med = statistics.median(self.values)
        mad = statistics.median(abs(v - med) for v in self.values)
        floor = self.REL_FLOOR * max(abs(self.mean or med), 1e-9)
        return max(1.4826 * mad, floor, 1e-12)

    def score(self, x: float) -> Optional[float]:
        if self.n < self.warmup or self.mean is None:
            return None
        return (float(x) - self.mean) / self.scale()

    def update(self, x: float):
        x = float(x)
        self.mean = (x if self.mean is None
                     else (1.0 - self.alpha) * self.mean + self.alpha * x)
        self.values.append(x)
        self.n += 1


def _parse_label_key(key: str) -> Dict[str, str]:
    """telemetry.read_series key ('k=v,k=v', '' for unlabeled) -> dict."""
    out: Dict[str, str] = {}
    for part in key.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k] = v
    return out


def _thread_stacks(stalled_ident: Optional[int] = None) \
        -> List[Dict[str, Any]]:
    """Every live thread's stack (sys._current_frames), the hang report's
    core forensic: which frame the stalled dispatch is wedged in."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        t = by_ident.get(ident)
        out.append({
            "name": t.name if t is not None else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t is not None else None,
            "stalled": ident == stalled_ident,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    return out


class Sentinel:
    """One supervision instance: rule baselines + alert ledger + dispatch
    watchdog. Construct directly for synchronous use (tests feed samples
    with `feed`, tick the watchdog with `check_hangs`); `start()` spawns
    the daemon poll and watchdog threads for live supervision."""

    def __init__(self, rules: Optional[Dict[str, Dict]] = None,
                 report_path: Optional[str] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 watch_tick_s: float = DEFAULT_WATCH_TICK_S,
                 hang_budget_s: Optional[float] = None):
        self.rules = dict(ALERT_CATALOG if rules is None else rules)
        self.report_path = report_path
        self.interval_s = float(interval_s)
        self.watch_tick_s = float(watch_tick_s)
        self._hang_budget_s = hang_budget_s
        self._baselines = {name: Baseline(warmup=rule["warmup"])
                           for name, rule in self.rules.items()}
        self._ledger: List[Dict[str, Any]] = []
        self._lock = threading.RLock()
        self._tokens = itertools.count(1)
        self._dispatches: Dict[int, Dict[str, Any]] = {}
        self.dispatches_total = 0
        self._hang: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # --- anomaly detection ---------------------------------------------------

    def feed(self, rule_name: str, value: float,
             now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Score one sample of one rule's series against its baseline,
        absorb it, and return the alert dict when a NEW ledger entry was
        raised (None when healthy, warming up, or deduplicated into an
        existing entry). `now` is the wall clock used for cooldown/ledger
        stamps — injectable so tests are deterministic."""
        rule = self.rules[rule_name]
        now = time.time() if now is None else float(now)
        with self._lock:
            base = self._baselines[rule_name]
            z = base.score(value)
            fired = None
            if z is not None:
                bad = (z >= rule["z"] if rule["direction"] == "high"
                       else z <= -rule["z"])
                if bad and rule["min_value"] is not None:
                    bad = (value >= rule["min_value"]
                           if rule["direction"] == "high"
                           else value <= rule["min_value"])
                if bad:
                    fired = self._raise(rule_name, rule, float(value), z,
                                        base, now)
            base.update(value)
            return fired

    def _raise(self, name, rule, value, z, base, now):
        # every caller today holds self._lock (RLock) via feed(); take
        # it explicitly so the ledger mutation below can never go bare
        # if a future caller arrives without it
        with self._lock:
            return self._raise_locked(name, rule, value, z, base, now)

    def _raise_locked(self, name, rule, value, z, base, now):
        for entry in reversed(self._ledger):
            if entry["rule"] != name:
                continue
            if now - entry["last_ts"] <= rule["cooldown_s"]:
                # same incident: dedup into the existing entry
                entry["count"] += 1
                entry["last_ts"] = now
                entry["value"] = value
                entry["zscore"] = z
                return None
            break  # cooldown elapsed: this is a new incident
        entry = {"rule": name, "severity": rule["severity"],
                 "metric": rule["metric"], "value": value, "zscore": z,
                 "baseline_mean": base.mean, "ts": now, "last_ts": now,
                 "count": 1, "host": telemetry._host_index(),
                 "help": rule["help"]}
        self._ledger.append(entry)
        del self._ledger[:-_LEDGER_CAP]
        telemetry.counter(
            "sentinel_alerts_total",
            "deduplicated sentinel alerts, by rule and severity",
            labels=("rule", "severity")).labels(
                rule=name, severity=rule["severity"]).inc()
        telemetry.log_event("alert", rule=name, severity=rule["severity"],
                            metric=rule["metric"], value=value, zscore=z)
        return dict(entry)

    def _read_rule(self, rule) -> Optional[float]:
        """Current live value of a rule's metric — read-only telemetry
        peeks only, so a quiet process never creates series."""
        entry = telemetry.METRIC_CATALOG.get(rule["metric"])
        labels = entry["labels"] if entry else ()
        if not labels:
            return telemetry.read_gauge(rule["metric"])
        vals = []
        lf = rule.get("label_filter")
        for key, v in telemetry.read_series(rule["metric"]).items():
            kv = _parse_label_key(key)
            if lf and any(kv.get(k) != str(w) for k, w in lf.items()):
                continue
            vals.append(float(v))
        if not vals:
            return None
        red = rule.get("reduce", "max")
        if red == "min":
            return min(vals)
        if red == "mean":
            return sum(vals) / len(vals)
        return max(vals)

    def poll(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One supervision pass: sample every rule's live metric (absent
        series are skipped, not zero-filled) and return new alerts."""
        fired = []
        for name, rule in self.rules.items():
            v = self._read_rule(rule)
            if v is None:
                continue
            a = self.feed(name, v, now=now)
            if a is not None:
                fired.append(a)
        return fired

    def alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._ledger]

    # --- hang watchdog -------------------------------------------------------

    def _budget_s(self) -> float:
        if self._hang_budget_s is not None:
            return float(self._hang_budget_s)
        raw = os.environ.get("PADDLE_TPU_SENTINEL_HANG_S", "").strip()
        if raw:
            try:
                return float(raw)
            except ValueError:
                pass
        last = telemetry.read_gauge("executor_last_step_seconds") or 0.0
        base = self._baselines.get("step_time_regression")
        rolling = base.mean if (base is not None and base.mean) else 0.0
        return max(HANG_FLOOR_S, HANG_MULTIPLIER * max(last, rolling))

    def arm(self, program: Optional[str] = None,
            budget_s: Optional[float] = None) -> int:
        """Register one in-flight dispatch; returns the token `disarm`
        takes. Deadline = now + max(60s, 20x rolling step time), or the
        PADDLE_TPU_SENTINEL_HANG_S / `budget_s` override."""
        budget = float(budget_s) if budget_s is not None \
            else self._budget_s()
        t = threading.current_thread()
        with self._lock:
            token = next(self._tokens)
            self.dispatches_total += 1
            self._dispatches[token] = {
                "program": program, "budget_s": budget,
                "started": time.monotonic(),
                "deadline": time.monotonic() + budget,
                "thread_ident": t.ident, "thread_name": t.name,
                "hung": False,
            }
        return token

    def disarm(self, token: int):
        with self._lock:
            info = self._dispatches.pop(token, None)
            recovered = (self._hang is not None
                         and self._hang.get("token") == token)
            if recovered:
                self._hang = None
        if recovered and info is not None:
            telemetry.log_event(
                "hang_recovered", program=info.get("program"),
                stalled_s=time.monotonic() - info["started"])

    def check_hangs(self, now_mono: Optional[float] = None):
        """Fire the hang handler for every armed dispatch past its
        deadline (the watchdog thread body; callable directly in tests)."""
        now = time.monotonic() if now_mono is None else now_mono
        fire = []
        with self._lock:
            for token, info in self._dispatches.items():
                if not info["hung"] and now >= info["deadline"]:
                    info["hung"] = True
                    fire.append((token, dict(info)))
        for token, info in fire:
            self._on_hang(token, info, now)

    def _on_hang(self, token, info, now_mono):
        path = (self.report_path
                or os.environ.get("PADDLE_TPU_SENTINEL_REPORT")
                or "paddle_tpu_hang.json")
        stacks = _thread_stacks(stalled_ident=info.get("thread_ident"))
        spans: List[Dict[str, Any]] = []
        try:
            from . import tracing
            spans = tracing.recent_spans(n=_SPAN_TAIL)
        except Exception:  # noqa: BLE001 - forensics are best-effort
            pass
        waited = now_mono - info["started"]
        err = TimeoutError(
            f"dispatch of '{info.get('program')}' exceeded its "
            f"{info['budget_s']:.3g}s hang deadline "
            f"(waited {waited:.3g}s)")
        report_path = None
        try:
            from . import inspector as inspector_mod
            report_path = inspector_mod.dump_crash_report(
                path, error=err, kind="hang",
                extra={"threads": stacks, "spans": spans,
                       "hang": {"program": info.get("program"),
                                "budget_s": info["budget_s"],
                                "waited_s": waited,
                                "thread": info.get("thread_name")}})
        except Exception:  # noqa: BLE001 - the verdict must still flip
            pass
        telemetry.counter(
            "sentinel_hangs_total",
            "hang-watchdog deadline expiries").inc()
        telemetry.log_event("hang", program=info.get("program"),
                            budget_s=info["budget_s"], waited_s=waited,
                            report=report_path)
        with self._lock:
            self._hang = {"reason": "hang", "ts": time.time(),
                          "program": info.get("program"),
                          "budget_s": info["budget_s"],
                          "thread": info.get("thread_name"),
                          "report_path": report_path, "token": token}
        print(f"paddle_tpu sentinel: hang detected "
              f"(program={info.get('program')}, "
              f"deadline {info['budget_s']:.3g}s)"
              + (f"; report written to {report_path} (read with "
                 f"`python -m paddle_tpu inspect {report_path}`)"
                 if report_path else ""),
              file=sys.stderr)

    def hang_state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return None if self._hang is None else dict(self._hang)

    def inject_stall(self, seconds: float, budget_s: float = 0.25,
                     program: str = "injected_stall") -> threading.Thread:
        """Drill helper (the --smoke stall and the hang tests): a thread
        that arms a dispatch and sleeps past its deadline, then disarms —
        exercising detection, the report dump, and clean recovery."""
        def _stalled_dispatch():
            tok = self.arm(program, budget_s=budget_s)
            try:
                time.sleep(seconds)
            finally:
                self.disarm(tok)

        th = threading.Thread(target=_stalled_dispatch,
                              name="sentinel-stall-drill", daemon=True)
        th.start()
        return th

    # --- threads -------------------------------------------------------------

    def start(self) -> "Sentinel":
        if self._threads:
            return self
        self._stop.clear()

        def _poll_loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll()
                except Exception:  # noqa: BLE001 - supervision never dies
                    pass

        def _watch_loop():
            while not self._stop.wait(self.watch_tick_s):
                try:
                    self.check_hangs()
                except Exception:  # noqa: BLE001
                    pass

        poll_t = threading.Thread(target=_poll_loop, daemon=True,
                                  name="paddle-tpu-sentinel-poll")
        watch_t = threading.Thread(target=_watch_loop, daemon=True,
                                   name="paddle-tpu-sentinel-watch")
        for t in (poll_t, watch_t):
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []


# --- process-wide singleton --------------------------------------------------

_LOCK = threading.Lock()
_SENTINEL: Optional[Sentinel] = None


def start(**kwargs) -> Sentinel:
    """Start (or return) the process-wide sentinel."""
    global _SENTINEL
    with _LOCK:
        if _SENTINEL is None:
            _SENTINEL = Sentinel(**kwargs).start()
        return _SENTINEL


def stop():
    global _SENTINEL
    # swap the singleton out under the lock, but join its threads
    # OUTSIDE it: stop() blocks up to the join timeout, and a concurrent
    # start()/arm_dispatch() must not wedge behind that
    with _LOCK:
        s, _SENTINEL = _SENTINEL, None
    if s is not None:
        s.stop()


def _current() -> Optional[Sentinel]:
    """Lock-free snapshot of the singleton. Executor/serving hot paths
    do exactly one attribute read per step; the reference assignment is
    atomic under the GIL and a momentarily stale value only skips (or
    double-feeds) a single supervision tick."""
    return _SENTINEL  # thread-lint: ok lockset-mixed-guard


def active() -> Optional[Sentinel]:
    return _current()


def enabled() -> bool:
    return _current() is not None


def reset():
    """Tear down the singleton (tests)."""
    stop()


def arm_dispatch(program: Optional[str] = None) -> Optional[int]:
    """Executor hook: one attribute check when the sentinel is off."""
    s = _current()
    return None if s is None else s.arm(program)


def disarm_dispatch(token: Optional[int]):
    s = _current()
    if token is not None and s is not None:
        s.disarm(token)


def hang_state() -> Optional[Dict[str, Any]]:
    s = _current()
    return None if s is None else s.hang_state()


def alert_summary(window_s: float = 600.0,
                  now: Optional[float] = None) -> Dict[str, Any]:
    """Compact alert state for /healthz: ledger totals, per-severity
    counts, and how many entries are still active (last fired within
    `window_s`) — active page-severity alerts degrade the verdict."""
    out: Dict[str, Any] = {"total": 0, "active": 0, "active_page": 0,
                           "by_severity": {}, "last": None}
    s = _current()
    if s is None:
        return out
    now = time.time() if now is None else now
    ledger = s.alerts()
    out["total"] = len(ledger)
    for a in ledger:
        sev = a["severity"]
        out["by_severity"][sev] = out["by_severity"].get(sev, 0) + 1
        if now - a["last_ts"] <= window_s:
            out["active"] += 1
            if sev == "page":
                out["active_page"] += 1
    if ledger:
        last = ledger[-1]
        out["last"] = {"rule": last["rule"], "severity": last["severity"],
                       "ts": last["ts"], "count": last["count"]}
    return out


def alerts_payload() -> Dict[str, Any]:
    """The /alerts endpoint body; well-formed even with no sentinel."""
    s = _current()
    return {
        "enabled": s is not None,
        "alerts": s.alerts() if s is not None else [],
        "hang": s.hang_state() if s is not None else None,
        "rules": sorted(ALERT_CATALOG),
        "summary": alert_summary(),
    }


def observe_loss(value: float, program: str = "p0"):
    """Publish a training-loss sample for the loss_spike rule. Training
    loops (and the smoke CLI) call this with the fetched loss scalar —
    the gauge is the bridge between user-side fetches and the rule
    catalog."""
    telemetry.gauge("train_loss",
                    "training loss observed by the run sentinel",
                    labels=("program",)).labels(program=program).set(
                        float(value))


def maybe_start_from_env() -> Optional[Sentinel]:
    """Honor PADDLE_TPU_SENTINEL: '1'/'true'/'on' starts the supervisor at
    import; anything else leaves it off."""
    raw = os.environ.get("PADDLE_TPU_SENTINEL", "").strip().lower()
    if raw in ("1", "true", "on", "yes"):
        return start()
    return None
