"""Program debugging: pretty printer + Graphviz DOT rendering
(reference: python/paddle/fluid/debuger.py pprint_program_codes /
draw_block_graphviz, python/paddle/fluid/graphviz.py, net_drawer.py).

`pprint_program` renders blocks as pseudo-code (vars with shapes/dtypes,
ops as calls); `draw_program` emits a Graphviz DOT graph (ops as boxes,
variables as ellipses, parameters highlighted) and optionally invokes
`dot` when available."""

from __future__ import annotations

import shutil
import subprocess
import warnings
from typing import Optional

__all__ = ["pprint_program", "draw_program"]


def _fmt_var(v) -> str:
    shape = "x".join(str(d) for d in (v.shape or [])) or "?"
    extra = ""
    if getattr(v, "persistable", False):
        extra += " persistable"
    if getattr(v, "lod_level", 0):
        extra += f" lod={v.lod_level}"
    return f"{v.name}: {v.dtype or '?'}[{shape}]{extra}"


def pprint_program(program, print_fn=print):
    """Pseudo-code program dump (reference debuger.py:131
    pprint_program_codes)."""
    for block in program.blocks:
        print_fn(f"// block {block.idx}"
                 + (f" (parent {block.parent_idx})"
                    if getattr(block, 'parent_idx', -1) not in (-1, None)
                    else ""))
        for name in sorted(block.desc.vars):
            v = block.desc.var(name)
            print_fn(f"  var {_fmt_var(v)}")
        for op in block.ops:
            outs = ", ".join(op.output_arg_names)
            ins = ", ".join(op.input_arg_names)
            attrs = {k: v for k, v in op.desc.attrs.items()
                     if not k.startswith("__")}
            a = ", ".join(f"{k}={v!r}" for k, v in sorted(attrs.items())
                          if not hasattr(v, "idx"))
            print_fn(f"  {outs or '()'} = {op.type}({ins}"
                     + (f" | {a}" if a else "") + ")")
        print_fn("")


def draw_program(program, path: Optional[str] = None, block_idx: int = 0,
                 render: bool = True) -> str:
    """Graphviz DOT for one block (reference debuger.py:33
    draw_block_graphviz, graphviz.py): op nodes are boxes, variables are
    ellipses, parameters are shaded. Returns the DOT source; writes
    `path` (.dot) and renders `<path>.pdf`/`.png` when `dot` exists and
    render=True."""
    block = program.block(block_idx)
    from .framework.framework import Parameter

    lines = ["digraph program {", '  rankdir=TB;',
             '  node [fontsize=10];']
    var_ids = {}

    def var_node(name):
        if name in var_ids:
            return var_ids[name]
        nid = f"var_{len(var_ids)}"
        var_ids[name] = nid
        v = block.desc.var(name) if block.desc.has_var(name) else None
        label = name if v is None else _fmt_var(v)
        is_param = isinstance(block.vars.get(name), Parameter)
        style = 'style=filled, fillcolor="#c9e4ca"' if is_param else \
            'style=filled, fillcolor="#f0f0f0"'
        lines.append(f'  {nid} [shape=ellipse, label="{label}", {style}];')
        return nid

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        lines.append(
            f'  {op_id} [shape=box, label="{op.type}", '
            f'style=filled, fillcolor="#a8d5e5"];')
        for name in op.input_arg_names:
            lines.append(f"  {var_node(name)} -> {op_id};")
        for name in op.output_arg_names:
            lines.append(f"  {op_id} -> {var_node(name)};")
    lines.append("}")
    dot = "\n".join(lines)

    if path:
        with open(path, "w") as f:
            f.write(dot)
        if render and shutil.which("dot"):
            for fmt in ("pdf", "png"):
                # a broken graphviz install (dot present but exiting
                # non-zero, or failing to exec) must not take down the
                # caller: the .dot source above is already on disk, so warn
                # and fall back to it
                try:
                    proc = subprocess.run(
                        ["dot", f"-T{fmt}", path, "-o", f"{path}.{fmt}"],
                        check=False, capture_output=True)
                except OSError as e:
                    warnings.warn(
                        f"graphviz 'dot' could not be executed ({e}); "
                        f"DOT source written to {path} only", RuntimeWarning)
                    break
                if proc.returncode != 0:
                    err = proc.stderr.decode("utf-8", "replace").strip()
                    warnings.warn(
                        f"'dot -T{fmt}' exited with status {proc.returncode}"
                        + (f": {err[:200]}" if err else "")
                        + f"; DOT source written to {path} only",
                        RuntimeWarning)
                    break
    return dot
