"""IR-level autodiff: append gradient ops to a Program.

TPU-native equivalent of the reference's source-to-source backward pass
(reference: python/paddle/fluid/backward.py:425 append_backward, :117
_addup_repetitive_outputs_, :167 no-grad pruning). Gradients are *ops in the
IR*, not a jax.grad closure — the program stays the product, serializable and
inspectable; JAX only executes it. Each forward op's grad ops come from the
registry's grad makers (generic vjp-backed by default, registry.py).

Fan-in accumulation: when several consumers contribute to one variable's
gradient, later contributions are renamed and summed eagerly (pairwise `sum`
ops), which is semantically the reference's @RENAME@ + sum_op insertion.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .framework.desc import OpDesc
from .framework.framework import (Block, Parameter, Program, Variable,
                                  grad_var_name)
from .ops import registry

__all__ = ["append_backward", "calc_gradient"]


def _collect_no_grad(block: Block, extra: Optional[Set[str]]) -> Set[str]:
    no_grad = set(extra or ())
    for name, v in block.vars.items():
        if getattr(v, "stop_gradient", False) or v.desc.stop_gradient:
            no_grad.add(name)
    return no_grad


def _relevant_op_indices(block: Block, loss_name: str) -> List[int]:
    """Backward slice: ops that (transitively) produce the loss."""
    target = {loss_name}
    idxs = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if target & set(op.output_arg_names):
            idxs.append(i)
            target |= set(op.input_arg_names)
    idxs.reverse()
    return idxs


def _ensure_grad_var(block: Block, gname: str):
    """Declare a grad var mirroring its forward var's shape/dtype."""
    if block.has_var(gname):
        return
    base = gname
    for marker in ("@RENAME@",):
        if marker in base:
            base = base.split(marker)[0]
    if base.endswith("@GRAD"):
        base = base[: -len("@GRAD")]
    if block.has_var_recursive(base):
        fv = block.var_recursive(base)
        block.create_var(name=gname, shape=fv.desc.shape, dtype=fv.dtype,
                         lod_level=fv.lod_level)
    else:
        block.create_var(name=gname)


# NO_GRAD ops that are legitimately gradient-transparent even when their
# outputs' grads are demanded: constants, shape/metadata probes, RNG sources,
# comparisons. NOT in this set: array read/write and other value-carrying
# ops — a zero grad through those is the silent-training-bug the check exists
# to catch (VERDICT r2 weak #6).
_ZERO_GRAD_SAFE = frozenset({
    "fill_constant", "fill_constant_batch_size_like", "fill_constant_tensor",
    "fill", "fill_zeros_like", "assign_value", "shape", "lod_rank_table",
    "max_sequence_len", "lod_array_length", "less_than", "less_equal",
    "greater_than", "greater_equal", "equal", "not_equal", "logical_and",
    "logical_or", "logical_not", "logical_xor", "is_empty",
    "one_hot", "uniform_random", "gaussian_random",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "sign", "arg_max", "arg_min", "crf_decoding", "ctc_align",
    "sequence_mask", "prior_box", "tensor_stats",
})

_INT_DTYPES = ("bool", "int8", "uint8", "int16", "int32", "int64")


def _check_silent_zero_grad(block: Block, fwd_op, no_grad: Set[str],
                            produced_count: Dict[str, int]):
    """Raise when a NO_GRAD op sits on the loss path with differentiable
    inputs: the reference errors out when no grad op is registered
    (op_registry GradOpMaker check); silently emitting nothing trains
    quietly wrong."""
    if os.environ.get("PADDLE_TPU_ALLOW_ZERO_GRAD", "0") == "1":
        return
    if fwd_op.type in _ZERO_GRAD_SAFE:
        return
    opdef = registry.try_get(fwd_op.type)
    if opdef is None or opdef.grad is not registry.NO_GRAD:
        return
    needed = [o for o in fwd_op.output_arg_names
              if grad_var_name(o) in produced_count]
    if not needed:
        return
    diff_ins = []
    for n in fwd_op.input_arg_names:
        if n in no_grad or not block.has_var_recursive(n):
            continue
        v = block.var_recursive(n)
        dt = getattr(v, "dtype", None) or getattr(v.desc, "dtype", None)
        if dt is None or str(dt) not in _INT_DTYPES:
            diff_ins.append(n)
    if diff_ins:
        raise RuntimeError(
            f"Operator '{fwd_op.type}' lies on the loss path (outputs "
            f"{needed} need gradients) but registers no gradient; its "
            f"differentiable inputs {diff_ins} would silently receive zero "
            f"gradient. Register a grad maker for '{fwd_op.type}', mark the "
            f"inputs stop_gradient, or set PADDLE_TPU_ALLOW_ZERO_GRAD=1 to "
            f"accept zero gradients.")


def append_backward(loss: Variable, parameter_list: Optional[Sequence] = None,
                    no_grad_set: Optional[Set[str]] = None,
                    callbacks=None) -> List[Tuple[Parameter, Variable]]:
    """Append grad ops for `loss` and return [(param, grad_var)].

    Only root-block autodiff is supported directly; control-flow ops carry
    their own sub-block grad logic via custom grad makers.
    """
    program = loss.block.program
    block = program.global_block()
    assert loss.block.idx == 0, "loss must live in the root block"
    no_grad = _collect_no_grad(block, no_grad_set)

    # record the loss var on the program: the inspector's auto probe mode
    # targets loss and grad vars, and grad_info_map alone cannot say which
    # forward var was the differentiation root
    losses = getattr(program, "_loss_names", None)
    if losses is None:
        losses = program._loss_names = []
    if loss.name not in losses:
        losses.append(loss.name)

    rel = _relevant_op_indices(block, loss.name)

    # Seed: d loss / d loss = 1
    loss_g = grad_var_name(loss.name)
    _ensure_grad_var(block, loss_g)
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_g]},
        attrs={"shape": list(loss.shape or [1]), "value": 1.0,
               "dtype": loss.dtype, "op_role": "backward"})

    produced_count: Dict[str, int] = {loss_g: 1}
    grad_to_var: Dict[str, str] = {loss_g: loss.name}

    for i in reversed(rel):
        fwd_op = block.ops[i]
        gdescs = registry.make_grad_op_descs(fwd_op.desc, no_grad)
        if not gdescs:
            _check_silent_zero_grad(block, fwd_op, no_grad, produced_count)
        for g in gdescs:
            # Rename duplicate grad writes, then accumulate with sum ops.
            # Exception: a grad op that CONSUMES n@GRAD and produces n@GRAD
            # mirrors a forward op that read-and-overwrote n (while loop
            # state, conditional_block carries, in-place ops). There the
            # output is the cotangent of the PRE-op value and must replace
            # the post-op cotangent — all fan-out consumers of the post-op
            # value were already summed in, since reverse-topo order visits
            # consumers before producers.
            g_grad_ins = {n for names in g.inputs.values() for n in names
                          if n.endswith("@GRAD")}
            renames: List[Tuple[str, str]] = []
            for slot, names in list(g.outputs.items()):
                new_names = []
                for n in names:
                    c = produced_count.get(n, 0)
                    if c == 0 or n in g_grad_ins:
                        produced_count[n] = max(c, 1)
                        new_names.append(n)
                    else:
                        rn = f"{n}@RENAME@{c}"
                        produced_count[n] = c + 1
                        new_names.append(rn)
                        renames.append((n, rn))
                g.outputs[slot] = new_names

            for slot, names in g.outputs.items():
                for n in names:
                    _ensure_grad_var(block, n)
                    base = n.split("@RENAME@")[0]
                    if base.endswith("@GRAD"):
                        grad_to_var[base] = base[: -len("@GRAD")]
            # role tag (reference OpRole::kBackward): inference slicing
            # (io.get_inference_program) strips these before pruning
            g.attrs.setdefault("op_role", "backward")
            block.desc.ops.append(g)
            from .framework.framework import Operator
            op_obj = Operator(block, g)
            block.ops.append(op_obj)
            program._version += 1
            block._infer_shape(op_obj)

            for orig, rn in renames:
                block.append_op(type="sum", inputs={"X": [orig, rn]},
                                outputs={"Out": [orig]},
                                attrs={"op_role": "backward"})

    program.grad_info_map.update(grad_to_var)

    if parameter_list is not None:
        params = [block.var(p) if isinstance(p, str) else p
                  for p in parameter_list]
    else:
        params = block.all_parameters()
    result = []
    for p in params:
        if not getattr(p, "trainable", True):
            continue
        gname = grad_var_name(p.name)
        if produced_count.get(gname):
            result.append((p, block.var(gname)))
    # record the (param, grad) pairing for the overlap pass
    # (parallel/overlap.py): grad names follow the grad_var_name
    # convention, but only append_backward knows which params actually
    # received a gradient in THIS program
    pairs = getattr(program, "_grad_param_pairs", None)
    if pairs is None:
        pairs = program._grad_param_pairs = []
    for p, g in result:
        ent = (p.name, g.name)
        if ent not in pairs:
            pairs.append(ent)
    # record grads that are SelectedRows by construction (is_sparse
    # lookup_table_grad): the overlap planner (parallel/overlap.py) must
    # not bucket them into dense all-reduces, and can say so at PLAN time
    # instead of discovering a sparse value at flush. Sharded tables force
    # sparse grads too, but sharding may be annotated after backward —
    # the planner cross-checks program._sharded_tables itself.
    sparse_names = getattr(program, "_sparse_grad_names", None)
    if sparse_names is None:
        sparse_names = program._sparse_grad_names = set()
    for op_ in block.ops:
        if op_.type == "lookup_table_grad" and op_.attr("is_sparse", False):
            for n in op_.output_arg_names:
                sparse_names.add(n)
    return result


def calc_gradient(targets, inputs, target_gradients=None,
                  no_grad_set: Optional[Set[str]] = None):
    """Gradients of `targets` w.r.t. `inputs` (reference backward.py:555).

    Supports multiple targets and optional initial cotangents: the combined
    gradient is built by differentiating sum_i reduce_sum(t_i * tg_i)
    (tg_i = ones when absent), which by linearity of the vjp equals the
    reference's multi-target accumulation. Like the reference, the grad ops
    are appended to the targets' program."""
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    elif not isinstance(target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    assert len(target_gradients) == len(targets), (
        f"{len(targets)} targets but {len(target_gradients)} target grads")
    program = targets[0].block.program
    from .framework.framework import program_guard
    from . import layers
    with program_guard(program):
        parts = []
        for t, tg in zip(targets, target_gradients):
            weighted = t if tg is None else layers.elementwise_mul(t, tg)
            parts.append(layers.reduce_sum(weighted))
        loss = parts[0]
        for p in parts[1:]:
            loss = layers.elementwise_add(loss, p)
    append_backward(loss, no_grad_set=no_grad_set)
    block = program.global_block()
    outs = []
    for v in inputs:
        gname = grad_var_name(v.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs
