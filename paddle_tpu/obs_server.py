"""Scrapeable live-observability endpoint (stdlib-only HTTP server).

A daemon-thread `ThreadingHTTPServer` that exposes the process's
telemetry surface while a training or serving workload runs in the
foreground threads:

    /metrics   Prometheus text exposition of the whole registry
    /healthz   liveness verdict: 200 JSON when healthy, 503 when steps
               have stalled (no run/run_window step event within the
               staleness threshold), a crash event was recorded, or the
               run sentinel's hang watchdog fired (reason=hang);
               "degraded" (still 200) when any model's fast-window SLO
               burn rate exceeds 1.0 or a page-severity sentinel alert
               is active
    /spans     recent finished trace spans (tracing.py ring buffer);
               ?n= limits, ?trace_id= filters, ?name= filters
    /alerts    run-sentinel alert ledger + hang state (sentinel.py)
    /report    roofline/fleet/SLO JSON roll-up
    /dynamics  training-dynamics observatory: per-series health verdicts
               + recent time-series (dynamics.py); ?n= limits rows
    /          endpoint index

Enable with `PADDLE_TPU_OBS_PORT=<port>` (picked up at import via
`maybe_start_from_env`), programmatically via `start(port=...)`, or with
the `python -m paddle_tpu obs` CLI subcommand. Port 0 binds an ephemeral
port (tests); the bound port is `server.port`.

The health verdict is deliberately conservative about silence: a process
that never ran a step (a pure serving process, say) is healthy — only a
process that *was* stepping and stopped inside the staleness threshold
flips to 503. The threshold defaults to max(60 s, 20x the last step's
wall time) and can be overridden per scrape with `?max_age=<seconds>`
(how the stall test flips it without waiting a minute).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from . import telemetry
from . import tracing

_LOCK = threading.Lock()
_SERVER: Optional["ObsServer"] = None

DEFAULT_MAX_STEP_AGE_S = 60.0
STEP_AGE_MULTIPLIER = 20.0


def health_report(max_step_age_s: Optional[float] = None,
                  now: Optional[float] = None) -> Dict[str, object]:
    """The /healthz verdict as a dict: {"status": "ok"|"degraded"|
    "unhealthy", "healthy": bool, "checks": {...}}. Pure function of the
    telemetry event ring + SLO registry so it is testable without HTTP."""
    now = time.time() if now is None else now
    last_step = None
    crash = None
    for ev in reversed(telemetry.recent_events()):
        kind = ev.get("kind")
        if last_step is None and kind in ("run", "run_window"):
            last_step = ev
        if crash is None and kind == "crash":
            crash = ev
        if last_step is not None and crash is not None:
            break

    checks: Dict[str, object] = {}
    healthy = True
    if last_step is None:
        # never stepped: not a training process, silence is not a stall
        checks["step"] = {"ran": False, "stalled": False}
    else:
        age = max(now - float(last_step.get("ts", now)), 0.0)
        last_s = telemetry.read_gauge("executor_last_step_seconds")
        threshold = (float(max_step_age_s) if max_step_age_s is not None
                     else max(DEFAULT_MAX_STEP_AGE_S,
                              STEP_AGE_MULTIPLIER * (last_s or 0.0)))
        stalled = age > threshold
        checks["step"] = {"ran": True, "age_s": age,
                          "threshold_s": threshold, "stalled": stalled,
                          "last_step_seconds": last_s}
        if stalled:
            healthy = False
    if crash is not None:
        checks["last_error"] = {"error": crash.get("error"),
                                "program": crash.get("program"),
                                "ts": crash.get("ts")}
        healthy = False
    else:
        checks["last_error"] = None

    degraded = False
    try:
        from .serving import slo as slo_mod
        slo_reports = slo_mod.all_reports()
        burns = {model: {w: r["windows"][w]["burn_rate"]
                         for w in ("fast", "slow")}
                 for model, r in slo_reports.items()}
        degraded = any(b["fast"] > 1.0 for b in burns.values())
        checks["slo"] = {"burn_rates": burns, "burning": degraded}
    except Exception:
        checks["slo"] = None

    # run sentinel: a detected hang is unhealthy (with a top-level
    # reason the drills/pagers key on); active page alerts degrade
    reason = None
    try:
        from . import sentinel as sentinel_mod
        hang = sentinel_mod.hang_state()
        checks["hang"] = hang
        if hang is not None:
            healthy = False
            reason = "hang"
        alerts = sentinel_mod.alert_summary(now=now)
        checks["alerts"] = alerts
        if alerts.get("active_page", 0) > 0:
            degraded = True
    except Exception:
        checks["hang"] = None
        checks["alerts"] = None

    status = ("unhealthy" if not healthy
              else "degraded" if degraded else "ok")
    out: Dict[str, object] = {"status": status, "healthy": healthy,
                              "checks": checks}
    if reason is not None:
        out["reason"] = reason
    return out


def _report_payload() -> Dict[str, object]:
    """/report: roll up the post-hoc reporters that exist in-process."""
    out: Dict[str, object] = {}
    try:
        from .serving import slo as slo_mod
        out["slo"] = slo_mod.all_reports()
    except Exception:
        out["slo"] = None
    try:
        from . import fleet
        out["goodput"] = fleet.goodput_report()
    except Exception:
        out["goodput"] = None
    # the roofline reporter publishes its headline numbers as gauges
    # (roofline.collect_report side effect); scrape those rather than
    # re-running a trace collection on a live process
    roofline_gauges = {}
    for gname in ("mfu_nominal", "mfu_vs_sustained",
                  "device_duty_cycle"):
        v = telemetry.read_gauge(gname)
        if v is not None:
            roofline_gauges[gname] = v
    out["roofline"] = roofline_gauges or None
    try:
        from . import sentinel as sentinel_mod
        out["sentinel"] = {"enabled": sentinel_mod.enabled(),
                           "summary": sentinel_mod.alert_summary(),
                           "hang": sentinel_mod.hang_state()}
    except Exception:
        out["sentinel"] = None
    snap = telemetry.snapshot()
    out["metrics_families"] = len(snap)
    out["spans_buffered"] = len(tracing.recent_spans())
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-obs/1.0"

    # silence per-request stderr lines — scrapes are periodic
    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj, default=str).encode(),
                   "application/json")

    def do_GET(self):
        parsed = urlparse(self.path)
        route = parsed.path.rstrip("/") or "/"
        q = parse_qs(parsed.query)
        telemetry.counter(
            "obs_requests_total", "observability endpoint scrapes",
            labels=("endpoint",)).labels(endpoint=route).inc()
        try:
            if route == "/metrics":
                text = telemetry.prometheus_text(telemetry.snapshot())
                self._send(200, text.encode(),
                           "text/plain; version=0.0.4")
            elif route == "/healthz":
                max_age = q.get("max_age", [None])[0]
                rep = health_report(
                    max_step_age_s=float(max_age)
                    if max_age is not None else None)
                self._send_json(200 if rep["healthy"] else 503, rep)
            elif route == "/spans":
                n = q.get("n", [None])[0]
                spans = tracing.recent_spans(
                    n=int(n) if n is not None else None,
                    name=q.get("name", [None])[0],
                    trace_id=q.get("trace_id", [None])[0])
                self._send_json(200, {"spans": spans,
                                      "enabled": tracing.enabled()})
            elif route == "/alerts":
                from . import sentinel as sentinel_mod
                self._send_json(200, sentinel_mod.alerts_payload())
            elif route == "/report":
                self._send_json(200, _report_payload())
            elif route == "/dynamics":
                from . import dynamics as dynamics_mod
                n = q.get("n", [None])[0]
                self._send_json(200, dynamics_mod.payload(
                    recent=int(n) if n is not None else 32))
            elif route == "/":
                self._send_json(200, {"endpoints": [
                    "/metrics", "/healthz", "/spans", "/alerts",
                    "/report", "/dynamics"]})
            else:
                self._send_json(404, {"error": f"no route {route}"})
        except BrokenPipeError:
            pass
        except Exception as e:  # a scrape must never kill the server
            try:
                self._send_json(500,
                                {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass


class ObsServer:
    """Background observability server. `start()` binds and spawns the
    daemon serve thread; `port` is the actually-bound port (useful with
    port=0)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._requested_port = int(port)
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1] if self._httpd is not None
                else None)

    def start(self) -> "ObsServer":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="paddle-tpu-obs",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def url(self, route: str = "/") -> str:
        return f"http://{self.host}:{self.port}{route}"


def start(port: int = 0, host: str = "127.0.0.1") -> ObsServer:
    """Start (or return) the process-wide observability server."""
    global _SERVER
    with _LOCK:
        if _SERVER is None:
            _SERVER = ObsServer(port=port, host=host).start()
        return _SERVER


def stop():
    global _SERVER
    # swap the singleton out under the lock, but run the HTTP shutdown +
    # thread join OUTSIDE it: stop() blocks until the serve loop exits,
    # and a concurrent start()/active() must not wedge behind that
    with _LOCK:
        s, _SERVER = _SERVER, None
    if s is not None:
        s.stop()


def active() -> Optional[ObsServer]:
    with _LOCK:
        return _SERVER


def maybe_start_from_env() -> Optional[ObsServer]:
    """Honor PADDLE_TPU_OBS_PORT: a port number starts the server on
    import (0 = ephemeral). Unset/empty/invalid leaves it off."""
    import os
    raw = os.environ.get("PADDLE_TPU_OBS_PORT", "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    try:
        return start(port=port)
    except OSError:
        return None
