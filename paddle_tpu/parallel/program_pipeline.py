"""Program-level pipeline parallelism: split a fluid Program into stage
sub-programs on the IR and train them under a GPipe microbatch schedule.

Reference ancestor: ParallelNeuralNetwork's layer-to-device assignment
(gserver/gradientmachines/ParallelNeuralNetwork.h) — whole layers pinned to
devices, activations shipped between them. Here the split happens on the
ProgramDesc: the user names the boundary (cut) variables, each stage becomes
a pruned sub-program (framework.prune dead-op elimination scoped to that
stage's slice), per-stage gradients are IR-level vjp programs built with
calc_gradient / append_backward, and per-stage optimizer-update programs are
emitted through the normal Optimizer pass. Execution runs the classic GPipe
schedule (forward all microbatches in tick order across per-stage devices,
then backward in reverse, accumulate, apply) — jax async dispatch overlaps
stage s of microbatch m with stage s+1 of microbatch m-1, which is the
pipeline. The homogeneous-stack SPMD variant lives in pipeline.py (gpipe);
this module is the heterogeneous Program/transpiler surface over it.

Numerics contract: with per-microbatch mean losses and equal microbatch
sizes, averaging the per-microbatch parameter gradients equals the
full-batch gradient, so losses match single-device training exactly
(tested on the 8-device CPU mesh, tests/test_program_pipeline.py).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["PipelineTranspiler"]


def _var_names(v_or_list):
    from ..framework.framework import Variable
    if isinstance(v_or_list, (list, tuple)):
        return [v.name if isinstance(v, Variable) else str(v)
                for v in v_or_list]
    v = v_or_list
    return [v.name if isinstance(v, Variable) else str(v)]


class _Stage:
    def __init__(self, idx, fwd_prog, grad_prog, update_prog, update_startup,
                 in_name, out_name, feed_names, param_names, grad_feed_name,
                 place):
        self.idx = idx
        self.fwd_prog = fwd_prog            # feeds -> out boundary (+loss)
        self.grad_prog = grad_prog          # feeds + cotangent -> grads
        self.update_prog = update_prog      # grad feeds -> param updates
        self.update_startup = update_startup
        self.in_name = in_name              # boundary var consumed (or None)
        self.out_name = out_name            # boundary var produced (or loss)
        self.feed_names = feed_names        # data vars this stage consumes
        self.param_names = param_names
        self.grad_feed_name = grad_feed_name  # cotangent feed (None on last)
        self.place = place


class PipelineTranspiler:
    """Split a program at named cut variables into pipeline stages.

    Usage (mirrors DistributeTranspiler's transpile-then-get pattern):

        t = PipelineTranspiler()
        trainer = t.transpile(loss, cut_vars=[h1, h2, h3],
                              optimizer=lambda: fluid.optimizer.SGD(0.1),
                              num_microbatches=4)
        exe_places = ...         # optional per-stage Places
        trainer.startup(startup_program)
        loss_val = trainer.train_step(feed={"x": ..., "y": ...})

    cut_vars define P = len(cut_vars)+1 stages: stage i computes from cut
    i-1 (or the feeds) up to cut i; the last stage ends at the loss.
    """

    def transpile(self, loss, cut_vars: Sequence, optimizer: Callable,
                  num_microbatches: int, main_program=None,
                  places: Optional[Sequence] = None):
        from .. import layers
        from ..backward import append_backward, calc_gradient
        from ..clip import append_gradient_clip_ops
        from ..executor import CPUPlace
        from ..framework.framework import (Parameter, grad_var_name,
                                           program_guard)
        from ..regularizer import append_regularization_ops

        program = main_program or loss.block.program
        cut_names = _var_names(list(cut_vars))
        loss_name = loss.name
        block = program.global_block()
        src_params = {p.name: p for p in block.all_parameters()}
        param_names_all = set(src_params)
        # non-trainable params stay frozen: excluded from grads and updates
        # exactly like append_backward's trainable filter in minimize()
        trainable = {n for n, p in src_params.items()
                     if getattr(p, "trainable", True)}
        data_names = self._feed_var_names(program)

        n_stages = len(cut_names) + 1
        boundaries = [None] + cut_names              # input boundary per stage
        targets = cut_names + [loss_name]            # output per stage

        stages: List[_Stage] = []
        for i in range(n_stages):
            in_name = boundaries[i]
            out_name = targets[i]
            feeds = ([in_name] if in_name else []) + list(data_names) \
                + sorted(param_names_all)
            fwd = program.prune(feeds=feeds, fetches=[out_name])
            fblock = fwd.global_block()
            # op_external_reads recurses into sub-blocks: a param or feed
            # read only inside a DynamicRNN/While body must still belong
            # to the stage, or it would silently never train/feed
            from ..framework.framework import op_external_reads
            stage_reads = set()
            for op in fblock.ops:
                stage_reads |= op_external_reads(fwd, op)
            stage_params = sorted(stage_reads & trainable)
            stage_feeds = sorted(stage_reads & set(data_names))

            # gradient program: stage forward + IR-level vjp
            grad = fwd.clone()
            gblock = grad.global_block()
            grad_feed_name = None
            with program_guard(grad):
                if i == n_stages - 1:
                    append_backward(gblock.var(out_name))
                else:
                    gvar = layers.data(
                        name=f"{out_name}@PIPE_CT", shape=[1],
                        dtype=gblock.var(out_name).dtype,
                        append_batch_size=False, stop_gradient=True)
                    grad_feed_name = gvar.name
                    wrt = ([in_name] if in_name else []) + stage_params
                    calc_gradient(gblock.var(out_name),
                                  [gblock.var(n) for n in wrt],
                                  target_gradients=gvar)

            # optimizer-update program: grads arrive as feeds
            from ..framework.framework import Program
            update = Program()
            update_startup = Program()
            with program_guard(update, update_startup):
                ublock = update.global_block()
                pg = []
                for pn in stage_params:
                    src = src_params[pn]
                    p = Parameter(ublock, name=pn, shape=src.shape,
                                  dtype=src.dtype)
                    # per-param optimizer semantics must survive the
                    # rebuild (lr scale, weight decay, clipping)
                    p.trainable = getattr(src, "trainable", True)
                    p.optimize_attr = dict(
                        getattr(src, "optimize_attr", None)
                        or {"learning_rate": 1.0})
                    p.regularizer = getattr(src, "regularizer", None)
                    p.gradient_clip_attr = getattr(
                        src, "gradient_clip_attr", None)
                    g = ublock.create_var(name=grad_var_name(pn),
                                          shape=src.shape, dtype=src.dtype)
                    pg.append((p, g))
                if pg:
                    opt = optimizer()
                    # same post-processing minimize() applies
                    pg = append_gradient_clip_ops(pg)
                    pg = append_regularization_ops(pg, opt.regularization)
                    opt._create_optimization_pass(pg, pg[0][0],
                                                  update_startup)
            place = None
            if places is not None:
                place = places[i % len(places)]
            stages.append(_Stage(i, fwd, grad, update, update_startup,
                                 in_name, out_name, stage_feeds,
                                 stage_params, grad_feed_name,
                                 place or CPUPlace(i)))

        # cut vars must be graph separators: a param reachable from two
        # stages (skip connection across a cut, or cuts out of topological
        # order) would get its optimizer update applied once per owning
        # stage — reject loudly instead of silently double-stepping.
        seen: Dict[str, int] = {}
        for s in stages:
            for pn in s.param_names:
                if pn in seen:
                    raise ValueError(
                        f"Parameter '{pn}' is used by pipeline stages "
                        f"{seen[pn]} and {s.idx}: the cut variables "
                        f"{cut_names} do not separate the graph (skip "
                        f"connection across a cut?). Choose cuts so every "
                        f"parameter belongs to exactly one stage.")
                seen[pn] = s.idx
        return PipelineTrainer(stages, num_microbatches, loss_name)

    @staticmethod
    def _feed_var_names(program) -> List[str]:
        """Data vars = root-block vars nobody produces and that are not
        parameters/persistable (the feed surface). Reads are collected
        recursively through sub-blocks (op_external_reads) so a feed
        consumed only inside control flow still counts."""
        from ..framework.framework import op_external_reads
        block = program.global_block()
        produced = {n for op in block.ops for n in op.output_arg_names}
        params = {p.name for p in block.all_parameters()}
        reads = set()
        for op in block.ops:
            reads |= op_external_reads(program, op)
        names = []
        for name in block.desc.vars:
            v = block.var(name)
            if name in produced or name in params:
                continue
            if getattr(v.desc, "persistable", False):
                continue
            if name in reads:
                names.append(name)
        return names


class PipelineTrainer:
    """GPipe execution of transpiled stages: forward all microbatches in
    tick order, backward reversed, average grads, apply updates."""

    def __init__(self, stages: List[_Stage], num_microbatches: int,
                 loss_name: str):
        from ..executor import Executor
        self.stages = stages
        self.m = num_microbatches
        self.loss_name = loss_name
        self.executors = [Executor(s.place) for s in stages]

    def startup(self, startup_program, scope=None):
        """Run the model's startup once (params init) + each stage's
        optimizer-startup (accumulators, lr vars)."""
        self.executors[0].run(startup_program, scope=scope)
        for s, exe in zip(self.stages, self.executors):
            exe.run(s.update_startup, scope=scope)

    def _split_feed(self, feed: Dict[str, np.ndarray]):
        from ..executor import LoDTensor
        m = self.m
        micro = [dict() for _ in range(m)]
        for name, val in feed.items():
            if isinstance(val, LoDTensor):
                # a LoD feed's packed rows cannot be row-sliced into
                # microbatches without splitting sequences mid-way; reject
                # loudly instead of silently corrupting boundaries
                raise ValueError(
                    f"Pipeline microbatching does not support LoDTensor "
                    f"feeds yet ('{name}'): pre-pad sequence feeds to "
                    f"dense [batch, time, ...] arrays")
            val = np.asarray(val)
            assert val.shape[0] % m == 0, (
                f"batch {val.shape[0]} not divisible into {m} microbatches")
            step = val.shape[0] // m
            for j in range(m):
                micro[j][name] = val[j * step: (j + 1) * step]
        return micro

    def train_step(self, feed: Dict[str, np.ndarray], scope=None):
        """One synchronized pipeline step over the full batch; returns the
        mean loss across microbatches."""
        from ..framework.framework import grad_var_name

        stages, m = self.stages, self.m
        p = len(stages)
        micro = self._split_feed(feed)

        # forward in GPipe tick order: async dispatch overlaps devices
        acts = [[None] * p for _ in range(m)]   # boundary outputs
        losses = [None] * m
        for t in range(m + p - 1):
            for si in range(p):
                j = t - si
                if not (0 <= j < m):
                    continue
                s, exe = stages[si], self.executors[si]
                f = {k: v for k, v in micro[j].items()
                     if k in s.feed_names}
                if s.in_name:
                    f[s.in_name] = acts[j][si - 1]
                out, = exe.run(s.fwd_prog, feed=f,
                               fetch_list=[s.out_name], scope=scope,
                               return_numpy=False)
                acts[j][si] = out
                if si == p - 1:
                    losses[j] = out

        # backward: reverse ticks; cotangents flow right-to-left
        grad_acc: Dict[str, object] = {}
        cts = [None] * m                         # cotangent entering stage si
        for t in range(m + p - 1):
            for si in range(p - 1, -1, -1):
                j = t - (p - 1 - si)
                if not (0 <= j < m):
                    continue
                s, exe = stages[si], self.executors[si]
                f = {k: v for k, v in micro[j].items()
                     if k in s.feed_names}
                if s.in_name:
                    f[s.in_name] = acts[j][si - 1]
                if s.grad_feed_name:
                    f[s.grad_feed_name] = cts[j]
                fetch = [grad_var_name(pn) for pn in s.param_names]
                if s.in_name:
                    fetch = [grad_var_name(s.in_name)] + fetch
                vals = exe.run(s.grad_prog, feed=f, fetch_list=fetch,
                               scope=scope, return_numpy=False)
                if s.in_name:
                    cts[j] = vals[0]
                    gvals = vals[1:]
                else:
                    gvals = vals
                for pn, gv in zip(s.param_names, gvals):
                    cur = grad_acc.get(pn)
                    grad_acc[pn] = gv if cur is None else cur + gv

        # apply: mean of microbatch grads == full-batch grad (mean losses)
        inv_m = 1.0 / m
        for s, exe in zip(stages, self.executors):
            if not s.param_names:
                continue
            gfeed = {grad_var_name(pn): grad_acc[pn] * inv_m
                     for pn in s.param_names}
            exe.run(s.update_prog, feed=gfeed, fetch_list=[], scope=scope)

        return float(np.mean([float(np.asarray(l).ravel()[0])
                              for l in losses]))
