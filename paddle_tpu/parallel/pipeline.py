"""Pipeline parallelism: an SPMD GPipe schedule over a 'pp' mesh axis.

The reference's closest ancestor is ParallelNeuralNetwork's layer-to-device
assignment (gserver/gradientmachines/ParallelNeuralNetwork.h) — whole
layers pinned to devices with activations shipped between them. The
TPU-native form is the collective-matmul-style SPMD pipeline: every device
runs the same stage function with ITS shard of the stacked stage
parameters, and activations hop one device per tick with `lax.ppermute`
while microbatches stream in (GPipe schedule, M microbatches over P
stages, M + P - 1 ticks, bubble fraction (P-1)/(M+P-1)).

Pure differentiable JAX: `jax.grad` through the pipeline matches the
sequential stage composition (tested on an 8-device host mesh). Stages
must share one structure (a homogeneous layer stack), which is the
standard GPipe setting."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ._collectives import coll_scope, tree_mark_varying as _pvary

__all__ = ["gpipe", "gpipe_reference"]


def gpipe_reference(stage_fn, stacked_params, x_microbatches):
    """Sequential oracle: apply stages 0..P-1 to every microbatch."""
    p = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def run_one(x):
        h = x
        for i in range(p):
            params_i = jax.tree_util.tree_map(lambda a: a[i], stacked_params)
            h = stage_fn(params_i, h)
        return h

    return jax.vmap(run_one)(x_microbatches)



def gpipe(stage_fn, stacked_params, x_microbatches, mesh, axis: str = "pp"):
    """Run `stage_fn(params_i, h) -> h` as a P-stage pipeline.

    stacked_params: pytree whose leaves stack the per-stage parameters on
    a leading axis of size P (sharded over `axis`, so each device holds
    only its stage's weights). x_microbatches: [M, B, ...] microbatches
    (replicated in; every device sees the stream but only stage 0 consumes
    it). Returns [M, B, ...] final-stage outputs (replicated out)."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    p_size = mesh.shape[axis]
    m = x_microbatches.shape[0]
    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P())
    def run(params_local, xs):
        # params_local leaves keep a leading axis of size 1 (the shard)
        params = jax.tree_util.tree_map(lambda a: a[0], params_local)
        idx = lax.axis_index(axis)
        ticks = m + p_size - 1
        zero_h = jnp.zeros(xs.shape[1:], xs.dtype)

        def tick(carry, t):
            recv, outs = carry
            # stage 0 injects microbatch t (while available); later stages
            # consume what arrived from the left neighbour last tick
            mb = xs[jnp.minimum(t, m - 1)]
            inp = jnp.where(idx == 0, mb, recv)
            h = stage_fn(params, inp)
            # last stage commits its result for microbatch t - (P-1)
            out_slot = t - (p_size - 1)
            commit = (idx == p_size - 1) & (out_slot >= 0)
            outs = lax.cond(
                commit,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, h, jnp.maximum(out_slot, 0), axis=0),
                lambda o: o, outs)
            # ship h one hop right (device i -> i+1)
            perm = [(i, i + 1) for i in range(p_size - 1)]
            with coll_scope("pipe_send"):
                nxt = lax.ppermute(h, axis, perm)
            return (nxt, outs), None

        outs0 = _pvary(jnp.zeros((m,) + xs.shape[1:], xs.dtype), axis)
        recv0 = _pvary(zero_h, axis)
        (_, outs), _ = lax.scan(tick, (recv0, outs0),
                                jnp.arange(ticks))
        # only the last device holds real outputs; replicate via psum
        with coll_scope("pipe_replicate"):
            return lax.psum(
                jnp.where(idx == p_size - 1, outs, jnp.zeros_like(outs)),
                axis)

    return run(stacked_params, x_microbatches)
