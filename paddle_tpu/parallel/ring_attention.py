"""Ring attention: sequence/context parallelism over a device mesh.

Long-context capability the 2018 reference lacks entirely (its sequence
story is LoD packing, SURVEY.md §2.5 last row); on TPU the natural design
is the ring schedule (Liu et al., Ring Attention; the 'How to Scale Your
Model' collective recipe): shard the sequence axis over an 'sp' mesh axis,
keep Q resident, and rotate K/V shards around the ring with
`lax.ppermute` while accumulating attention in the numerically stable
online-softmax (flash) form. Peak memory per device is O(T/P) sequence
and O(T/P * T/P) scores — full-sequence attention never materializes —
and the K/V rotation rides ICI concurrently with compute.

Everything is pure differentiable JAX: `ppermute` has a transpose rule,
so `jax.grad` of the ring matches the single-device attention gradient
(tested to 1e-5 on an 8-device host mesh)."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["attention_reference", "ring_attention", "ring_attention_sharded",
           "ring_attention_bwd_sharded", "flash_ring_eligible"]


def _scaled_masked_logits(q, k, causal, scale):
    """The one definition of the attention scores [B, H, Tq, Tk]:
    attention_reference and attention_reference_lse MUST build logits
    through this single helper — the einsum-path backward's correctness
    (LSE consistent with the probs) and the XLA-CSE performance story
    both depend on the two being the identical computation."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    return logits


def attention_reference(q, k, v, causal: bool = False, scale=None):
    """Plain softmax attention, q/k/v [B, T, H, D] -> [B, T, H, D]."""
    probs = jax.nn.softmax(_scaled_masked_logits(q, k, causal, scale),
                           axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attention_reference_lse(q, k, causal: bool = False, scale=None):
    """Per-row logsumexp of the scaled (masked) scores [B, H, T] in f32 —
    the LSE residual the flash kernels save; here derived from the same
    logits XLA CSEs with attention_reference's einsum."""
    return jax.scipy.special.logsumexp(
        _scaled_masked_logits(q, k, causal, scale).astype(jnp.float32),
        axis=-1)


def _block_attn(q, k, v, scale, mask):
    """Unnormalized blockwise attention: returns (acc, row_sum, row_max)
    in the online-softmax form. q [B,Tq,H,D], k/v [B,Tk,H,D],
    mask [Tq,Tk] bool or None."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)                      # [B,H,Tq]
    # all-masked rows produce -inf max; exp(-inf - -inf) would NaN
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])           # [B,H,Tq,Tk]
    l = jnp.sum(p, axis=-1)                           # [B,H,Tq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return acc, l, m_safe, jnp.isfinite(m)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale=None, use_flash: bool = False,
                   return_lse: bool = False):
    """Attention over a sequence sharded on `axis_name` (call inside
    shard_map / pjit with that axis). q/k/v are the LOCAL shards
    [B, T/P, H, D]; returns the local output shard (with the per-row
    scaled-score logsumexp [B, H, T/P] when return_lse — the residual the
    flash ring backward consumes).

    Each of the P ring steps attends the resident Q against the visiting
    K/V shard and merges via online softmax; `ppermute` then rotates the
    K/V shard (and its global offset) one hop — on hardware meshes the
    send overlaps the next block's compute on ICI."""
    p_size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    elif use_flash:
        # the pallas block kernel bakes scale in as a compile-time
        # constant; a traced scale falls back to the einsum path instead
        # of raising an opaque concretization error (ADVICE r3)
        try:
            scale = float(scale)
        except (TypeError, jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError):
            use_flash = False
    if use_flash:
        from ..ops.pallas_attention import block_supports
        if not block_supports(q, k):
            use_flash = False        # shard shapes not tileable: einsum

    q_pos = idx * t_local + jnp.arange(t_local)       # global q positions

    def step(carry, _):
        k_cur, v_cur, k_off, acc, l_acc, m_acc, any_valid = carry
        if use_flash:
            # per-shard compute on the Pallas flash kernel
            # (ops/pallas_attention.flash_attention_block): VMEM online
            # softmax within the shard, ring merge across shards
            from ..ops.pallas_attention import flash_attention_block
            acc_b, l_b, m_b = flash_attention_block(
                q, k_cur, v_cur, idx * t_local, k_off, scale, causal)
            valid_b = m_b > -5e29
            m_b = jnp.where(valid_b, m_b, 0.0)
            acc_b = acc_b.astype(acc.dtype)
            l_b = l_b.astype(l_acc.dtype)
            m_b = m_b.astype(m_acc.dtype)
        else:
            if causal:
                kv_pos = k_off + jnp.arange(t_local)
                mask = q_pos[:, None] >= kv_pos[None, :]
            else:
                mask = None
            acc_b, l_b, m_b, valid_b = _block_attn(q, k_cur, v_cur, scale,
                                                   mask)
        # online-softmax merge of (acc, l, m) with the new block. Rows the
        # visiting block fully masks must not move the running max (their
        # clamped m_b of 0.0 would destroy the subtraction invariant when
        # the true row max is negative).
        m_new = jnp.where(valid_b, jnp.maximum(m_acc, m_b), m_acc)
        alpha = jnp.exp(m_acc - m_new)                # rescale old
        # invalid rows must not contribute: mask the EXPONENT (exp(-inf)=0)
        # rather than the value — where(valid, exp(big), 0) would still
        # compute an inf whose where-VJP yields 0*inf = NaN gradients
        beta = jnp.exp(jnp.where(valid_b, m_b - m_new, -jnp.inf))
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + \
            acc_b * beta.transpose(0, 2, 1)[..., None]
        l_acc = l_acc * alpha + l_b * beta
        m_acc = m_new
        any_valid = any_valid | valid_b

        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        from ._collectives import coll_scope
        with coll_scope("ring_kv_rotate"):
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
            off_nxt = lax.ppermute(k_off, axis_name, perm)
        return (k_nxt, v_nxt, off_nxt, acc, l_acc, m_acc, any_valid), None

    from ._collectives import mark_varying

    def _vary(x):
        # shard_map type-checks varying-manifest axes on scan carries;
        # replicated-initialized carries must be marked varying explicitly
        return mark_varying(x, axis_name)

    acc0 = _vary(jnp.zeros((b, t_local, h, d), q.dtype))
    l0 = _vary(jnp.zeros((b, h, t_local), q.dtype))
    m0 = _vary(jnp.full((b, h, t_local), -jnp.inf, q.dtype))
    valid0 = _vary(jnp.zeros((b, h, t_local), bool))
    k_off0 = idx * t_local
    (_, _, _, acc, l_acc, m_acc, _), _ = lax.scan(
        step, (k, v, k_off0, acc0, l0, m0, valid0), None, length=p_size)
    out = acc / jnp.maximum(l_acc, 1e-30).transpose(0, 2, 1)[..., None]
    if return_lse:
        lse = m_acc.astype(jnp.float32) + jnp.log(
            jnp.maximum(l_acc.astype(jnp.float32), 1e-30))
        return out, lse
    return out


def _ring_bwd_local(q, k, v, do, o, lse, axis_name, causal, scale):
    """Flash ring backward (local shards, call inside shard_map): the same
    ring schedule as the forward, but each step computes the (dQ, dK, dV)
    block gradients between the resident Q and the visiting K/V shard on
    the Pallas backward kernels; dQ accumulates locally while the dK/dV
    accumulators rotate WITH their K/V shard, arriving home complete after
    P hops. Memory stays O(T/P) — no einsum recompute, no [Tq, Tk] scores
    (closes VERDICT r3 missing #1 / weak #1)."""
    from ..ops.pallas_attention import flash_attention_bwd_block
    from ._collectives import mark_varying

    p_size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    # delta_i = dO_i . O_i (softmax-jacobian row correction), [B, H, T/P]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)
    q_off = idx * t_local

    def _vary(x):
        return mark_varying(x, axis_name)

    def step(carry, _):
        k_cur, v_cur, dk_cur, dv_cur, k_off, dq = carry
        dq_b, dk_b, dv_b = flash_attention_bwd_block(
            q, k_cur, v_cur, do, lse, delta, q_off, k_off, scale, causal)
        dq = dq + dq_b.astype(jnp.float32)
        dk_cur = dk_cur + dk_b.astype(jnp.float32)
        dv_cur = dv_cur + dv_b.astype(jnp.float32)
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        from ._collectives import coll_scope
        with coll_scope("ring_bwd_rotate"):
            return (lax.ppermute(k_cur, axis_name, perm),
                    lax.ppermute(v_cur, axis_name, perm),
                    lax.ppermute(dk_cur, axis_name, perm),
                    lax.ppermute(dv_cur, axis_name, perm),
                    lax.ppermute(k_off, axis_name, perm), dq), None

    def zeros():
        return _vary(jnp.zeros((b, t_local, h, d), jnp.float32))

    (_, _, dk, dv, _, dq), _ = lax.scan(
        step, (k, v, zeros(), zeros(), q_off, zeros()), None, length=p_size)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _shard_map_fn():
    try:
        from jax import shard_map
    except ImportError:              # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map


def _sm(mesh, flash, **smkw):
    # check_vma off on the flash path: the pallas HLO interpreter's
    # dynamic_slice hits a varying-manifest false positive when inputs
    # alias (jax suggests exactly this workaround in its error).
    # Probe the signature — functools.partial would defer an unknown-
    # kwarg TypeError to the call site, past any try/except here.
    shard_map = _shard_map_fn()
    kw = {}
    if flash:
        import inspect
        try:
            if "check_vma" in inspect.signature(shard_map).parameters:
                kw["check_vma"] = False
        except (TypeError, ValueError):
            pass
    return functools.partial(shard_map, mesh=mesh, **kw, **smkw)


def flash_ring_eligible(q, mesh, axis: str = "sp") -> bool:
    """Static check: can the flash (Pallas) ring run for this global shape
    on this mesh? The per-shard sequence length must divide evenly and tile
    (mirrors ops.pallas_attention.block_supports on the shard shape). Both
    the forward op and its explicit grad op consult this, so the backward
    never has to re-run the forward to find out which path it took."""
    n_sp = mesh.shape[axis]
    if q.shape[1] % n_sp != 0:
        return False
    from ..ops.pallas_attention import block_supports
    probe = jax.ShapeDtypeStruct(
        (q.shape[0], q.shape[1] // n_sp) + tuple(q.shape[2:]), q.dtype)
    return block_supports(probe, probe)


def ring_attention_sharded(q, k, v, mesh, axis: str = "sp",
                           causal: bool = False, use_flash: bool = False,
                           return_lse: bool = False):
    """Convenience wrapper: global q/k/v [B, T, H, D] -> shard_map the ring
    over mesh axis `axis` (T must divide by the axis size). use_flash=True
    runs flash end-to-end: the per-shard blocks on the Pallas kernels in
    BOTH directions (forward online-softmax blocks; backward dQ/dK/dV
    blocks recomputed from the saved logsumexp), the ring across shards.
    Shard shapes that don't tile fall back to the einsum ring, whose
    backward differentiates through the scan.

    return_lse=True additionally returns the global per-row logsumexp
    [B, H, T] (f32) — the residual `ring_attention_bwd_sharded` consumes,
    letting an explicit grad op skip re-running the forward (Pallas custom
    calls are not CSE'd, so a vjp re-trace would pay the flash forward
    twice per step)."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    lse_spec = P(None, None, axis)

    def _make(flash, lse):
        out_specs = (spec, lse_spec) if lse else spec

        @_sm(mesh, flash, in_specs=(spec, spec, spec), out_specs=out_specs)
        def run(ql, kl, vl):
            return ring_attention(ql, kl, vl, axis_name=axis,
                                  causal=causal, use_flash=flash,
                                  return_lse=lse)
        return run

    flash_ok = use_flash and flash_ring_eligible(q, mesh, axis)
    if not flash_ok:
        return _make(False, return_lse)(q, k, v)

    if return_lse:
        # caller owns the backward (ring_attention_bwd_sharded)
        return _make(True, True)(q, k, v)

    scale = 1.0 / float(q.shape[-1]) ** 0.5

    @jax.custom_vjp
    def flash_ring(q, k, v):
        return _make(True, False)(q, k, v)

    def fwd(q, k, v):
        o, lse = _make(True, True)(q, k, v)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        qr, kr, vr, o, lse = res
        return ring_attention_bwd_sharded(qr, kr, vr, g, o, lse, mesh,
                                          axis=axis, causal=causal,
                                          scale=scale)

    flash_ring.defvjp(fwd, bwd)
    return flash_ring(q, k, v)


def ring_attention_bwd_sharded(q, k, v, do, o, lse, mesh, axis: str = "sp",
                               causal: bool = False, scale=None):
    """Direct flash-ring backward from the saved (O, LSE) residuals: dQ/dK/
    dV via the Pallas backward kernels on the same ring schedule — no
    forward re-execution (the saved LSE is exactly what the blockwise
    backward needs). Requires `flash_ring_eligible`."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    lse_spec = P(None, None, axis)
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5

    @_sm(mesh, True, in_specs=(spec, spec, spec, spec, spec, lse_spec),
         out_specs=(spec, spec, spec))
    def _bwd(ql, kl, vl, dol, ol, lsel):
        return _ring_bwd_local(ql, kl, vl, dol, ol, lsel, axis_name=axis,
                               causal=causal, scale=scale)

    return _bwd(q, k, v, do, o, lse)
