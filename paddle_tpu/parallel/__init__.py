"""Parallelism: mesh-based SPMD replacing the reference's parameter-server /
NCCL / parallel_do stack (SURVEY.md §2.5). See `mesh.py` and `transpiler.py`."""

from . import mesh
from .mesh import get_mesh, set_mesh, data_parallel_mesh
from . import transpiler
from . import multihost
from . import master
from . import tensor_parallel
from .tensor_parallel import (shard_parameter, shard_fc_params,
                              shard_all_params_zero)
from . import ring_attention
from . import planner
from .planner import SpecLayout, mesh_from_env, validate_plan_bytes
from . import embedding
from .embedding import (shard_table, shard_embeddings,
                        per_shard_table_bytes)
from . import emb_cache
from . import pipeline
from .pipeline import gpipe
from . import program_pipeline
from .program_pipeline import PipelineTranspiler
from .ring_attention import ring_attention_sharded


def shard_feed(program, name, spec):
    """Override a feed variable's mesh sharding (dims -> axis name or
    None), e.g. shard_feed(prog, "tokens", (None, "sp")) to split the
    sequence axis for ring attention."""
    if not hasattr(program, "_feed_shardings"):
        program._feed_shardings = {}
    program._feed_shardings[name] = tuple(spec)
    return program


def per_shard_param_bytes(program, scope=None):
    """Per-device parameter bytes under the program's mesh: a parameter
    annotated in `_param_shardings` (shard_parameter/shard_fc_params/
    shard_all_params_zero) occupies size/prod(sharded axis sizes) HBM per
    device under GSPMD; everything else is fully replicated. Complements
    `Executor.static_memory_analysis`, whose memory_analysis() of an SPMD
    program is already per-shard (XLA partitions the module before buffer
    assignment) — this splits the same number into replicated-vs-sharded
    so sweeps (tools/scaling_bench) can see WHY the footprint scales.
    Returns {devices, replicated_bytes, sharded_bytes_per_device,
    per_device_bytes, by_axes, params}. `by_axes` partitions the
    per-device bytes by the axis-name set each param shards over —
    "replicated", "fsdp", "fsdp+tp", ... — the breakdown the planner's
    byte validation and the SCALE_MODEL=lm bench lines report."""
    from .. import executor as executor_mod
    from .. import memory as memory_mod

    scope = scope if scope is not None else executor_mod.global_scope()
    m = getattr(program, "_mesh", None)
    axis_sizes = dict(m.shape) if m is not None else {}
    n_dev = 1
    for s in axis_sizes.values():
        n_dev *= int(s)
    specs = getattr(program, "_param_shardings", {}) or {}
    replicated = sharded = 0
    detail = {}
    by_axes = {}
    for p in program.global_block().all_parameters():
        v = scope.find_var(p.name)
        b = memory_mod.nbytes_of(v)
        if not b:
            continue
        factor = 1
        spec_axes = set()
        for ent in specs.get(p.name) or ():
            # dim entries may be one axis ("fsdp") or an axis tuple
            # (("fsdp", "tp") — embedding.SpecLayout row sharding)
            axes = (tuple(ent) if isinstance(ent, (tuple, list))
                    else (ent,) if ent else ())
            for ax in axes:
                factor *= int(axis_sizes.get(ax, 1))
                spec_axes.add(str(ax))
        if factor > 1:
            per_dev = -(-b // factor)   # ceil: XLA pads uneven shards
            sharded += per_dev
            key = "+".join(sorted(spec_axes))
            detail[p.name] = {"bytes": b, "per_device": per_dev,
                              "factor": factor, "axes": key}
        else:
            per_dev = b
            replicated += b
            key = "replicated"
            detail[p.name] = {"bytes": b, "per_device": b, "factor": 1,
                              "axes": key}
        by_axes[key] = int(by_axes.get(key, 0) + per_dev)
    return {"devices": n_dev, "replicated_bytes": int(replicated),
            "sharded_bytes_per_device": int(sharded),
            "per_device_bytes": int(replicated + sharded),
            "by_axes": by_axes, "params": detail}
