"""Parallelism: mesh-based SPMD replacing the reference's parameter-server /
NCCL / parallel_do stack (SURVEY.md §2.5). See `mesh.py` and `transpiler.py`."""

from . import mesh
from .mesh import get_mesh, set_mesh, data_parallel_mesh
from . import transpiler
from . import multihost
from . import tensor_parallel
from .tensor_parallel import (shard_parameter, shard_fc_params,
                              shard_all_params_zero)
