"""Parallelism: mesh-based SPMD replacing the reference's parameter-server /
NCCL / parallel_do stack (SURVEY.md §2.5). See `mesh.py` and `transpiler.py`."""

from . import mesh
from .mesh import get_mesh, set_mesh, data_parallel_mesh
from . import transpiler
from . import multihost
from . import master
from . import tensor_parallel
from .tensor_parallel import (shard_parameter, shard_fc_params,
                              shard_all_params_zero)
from . import ring_attention
from . import pipeline
from .pipeline import gpipe
from . import program_pipeline
from .program_pipeline import PipelineTranspiler
from .ring_attention import ring_attention_sharded


def shard_feed(program, name, spec):
    """Override a feed variable's mesh sharding (dims -> axis name or
    None), e.g. shard_feed(prog, "tokens", (None, "sp")) to split the
    sequence axis for ring attention."""
    if not hasattr(program, "_feed_shardings"):
        program._feed_shardings = {}
    program._feed_shardings[name] = tuple(spec)
    return program
