"""DistributeTranspiler: source-compatible facade over mesh sharding.

The reference rewrites the program into trainer programs (send ops) and
parameter-server programs (listen_and_serv) over gRPC
(reference: python/paddle/fluid/distribute_transpiler.py:134 transpile,
:258 get_pserver_program). On TPU the whole tier collapses into synchronous
AllReduce data parallelism over ICI: `transpile` tags the program with a
device mesh; the executor then runs it SPMD with feeds sharded along the
batch axis and XLA inserting the gradient AllReduce. `get_pserver_program`
has no role (there are no parameter servers) and raises with guidance.
"""

from __future__ import annotations

from typing import Optional

from ..framework.framework import Program, default_main_program
from . import mesh as mesh_mod

__all__ = ["DistributeTranspiler", "memory_optimize", "release_memory"]


class DistributeTranspiler:
    def transpile(self, trainer_id: int = 0, program: Optional[Program] = None,
                  pservers: str = "", trainers: int = 1,
                  split_method=None, mesh=None):
        """Tag `program` for SPMD data-parallel execution over `trainers`
        devices (or an explicit mesh)."""
        self.program = program or default_main_program()
        self.trainer_id = trainer_id
        self.trainers = trainers
        if mesh is None:
            mesh = mesh_mod.data_parallel_mesh(
                None if trainers <= 1 else trainers)
        self.mesh = mesh
        self.program._mesh = mesh
        return self

    def get_trainer_program(self) -> Program:
        return self.program

    def get_pserver_program(self, endpoint=None):
        raise RuntimeError(
            "There are no parameter servers on TPU: the transpiled program "
            "runs synchronous AllReduce data parallelism over ICI. Run the "
            "trainer program on every host (jax.distributed) instead.")

    def get_startup_program(self, endpoint=None, pserver_program=None):
        raise RuntimeError(
            "No pserver startup program on TPU; run the normal startup "
            "program once per host.")


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    """Reference memory_optimization_transpiler.py:332 rewrote var reuse;
    under XLA, buffer liveness/reuse is the compiler's job, so this is a
    documented no-op kept for source compatibility."""
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
