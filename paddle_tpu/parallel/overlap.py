"""Communication/compute overlap for data-parallel training (ISSUE 9).

The reference's ParallelExecutor earned its multi-device speed from
dependency-graph scheduling: each gradient's NCCL all-reduce launches as
soon as backward produces it, overlapped with the rest of backward
(PAPER.md §fluid distributed). On TPU the collective itself is inserted
by XLA's GSPMD partitioner, so the lever moves from "launch NCCL
eagerly" to "give XLA's scheduler room to hide the ICI time". This
module is that lever, in three layers:

1. **Bucketed eager gradient sync** — `plan()` groups a dp-mesh-tagged
   program's parameter gradients into size-capped per-dtype buckets in
   readiness order (ascending last-producer op index: the order backward
   finishes them) and the executor flushes each bucket at trace time
   immediately after its last producing grad op. A flush pins every
   member gradient to the replicated sharding under a
   `pd.coll.dp_grad_bucket<i>` named scope — a pure annotation, so
   numerics stay bitwise vs. the unscheduled trace — which moves the
   partial-sum -> replicated resolution point from "lazily, where the
   optimizer consumes the grad" to "eagerly, the moment the grad is
   ready", exactly the slack the latency-hiding scheduler needs to
   overlap the all-reduce with the remaining backward compute.

2. **Latency-hiding schedule plumbing** — `compiler_options()` returns
   the async-collective + latency-hiding-scheduler XLA options for the
   executor's single `jax.jit` call site (`Executor._jit_compile`, both
   the per-step and the `run_steps` scan path). Options are gated to the
   TPU backend (CPU/GPU XLA rejects them at first call) and validated
   once per process by compiling a trivial probe; a rejected set degrades
   to no options and counts an `overlap_fallback_total` reason.

3. **Auto steps-per-call** — `choose_steps_per_call()` picks the
   dispatch-amortization window K from the measured per-step Python
   overhead (K large enough that host dispatch is <= a target fraction
   of device time) bounded by the HBM headroom left after the K=1
   footprint, via memory.py's HeadroomModel (the window feed buffer
   scales linearly in K the way activations scale in batch).

Env knobs: `PADDLE_TPU_OVERLAP=1` (default on) gates all three layers;
`PADDLE_TPU_OVERLAP_BUCKET_MB` caps bucket size (default 4 MiB, read at
plan time); `PADDLE_TPU_OVERLAP_XLA_FLAGS="k=v,k=v"` overrides the
compiler-option set on any backend (still probe-validated). Per-reason
`overlap_fallback_total{program,reason}` mirrors fusion_fallback_total:
tp_sharded (model-parallel grads, no cross-dp sum to schedule) /
sharded_param (spec names an axis the mesh lacks) / missing_grad /
sparse_grad / constraint_failed at the bucket layer, platform /
rejected_options at the compile layer. Since the planner (ISSUE 15),
dp/fsdp-sharded params no longer skip: their grads bucket per
(dtype, spec) group and flush as eager reduce-scatters.

GSPMD attribution caveat: the all-reduce HLO instructions inherit the
*producer's* op_name metadata (the grad op), not the bucket scope — the
sharding-constraint nodes carrying `pd.coll.dp_grad_bucket<i>` are
compiled away into the neighbouring fusions. fleet.collective_table
therefore pools real dp-grad collectives under `(gspmd:<op>)` labels;
the per-bucket sites appear wherever the partitioner materializes
collectives at the constraint itself (resharding paths) and in the
synthetic-xplane tests that pin the reporting machinery.
"""

from __future__ import annotations

import bisect
import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "OVERLAP_OPT", "Bucket", "OverlapPlan", "plan", "count_fallback",
    "compiler_options", "TPU_OVERLAP_OPTIONS", "choose_steps_per_call",
]

# default ON; PADDLE_TPU_OVERLAP=0 restores the unscheduled trace and
# plain jit compiles (the bitwise-parity baseline)
OVERLAP_OPT = os.environ.get("PADDLE_TPU_OVERLAP", "1") == "1"


def _bucket_cap_bytes() -> int:
    """Per-bucket payload cap. Read at plan time so tests can shrink it
    (a tiny cap forces multiple buckets out of KB-sized test models)."""
    try:
        mb = float(os.environ.get("PADDLE_TPU_OVERLAP_BUCKET_MB", "4"))
    except ValueError:
        mb = 4.0
    return max(int(mb * 1024 * 1024), 1)


def count_fallback(program, reason: str, amount: int = 1):
    """overlap_fallback_total{program,reason} — the same per-reason
    telemetry shape as fusion_fallback_total / executor_window_fallback."""
    from .. import telemetry
    telemetry.counter(
        "overlap_fallback_total",
        "gradients or compile paths that kept the unscheduled sync by "
        "reason (communication/compute overlap pass)",
        labels=("program", "reason")).labels(
        program=telemetry.program_label(program), reason=reason).inc(amount)


# --------------------------------------------------------------------------
# Layer 1: bucketed eager gradient sync (trace-time pass)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Bucket:
    """One flush unit: `grads[i]` is the gradient of `params[i]`, all the
    same declared dtype AND the same parameter spec group, total payload
    <= the plan-time cap. `anchor` is the global-block index of the LAST
    op producing any member gradient — the executor flushes the bucket
    right after that op executes. `spec` is the spec group's entry tuple:
    empty for replicated params (the pure-dp case, pinned to the
    replicated sharding = eager all-reduce) and the parameter's own spec
    for ZeRO/fsdp-sharded ones (pinned to the param spec = eager
    reduce-scatter)."""
    index: int
    params: Tuple[str, ...]
    grads: Tuple[str, ...]
    dtype: str
    bytes: int
    anchor: int
    spec: Tuple = ()

    @property
    def site(self) -> str:
        prefix = "_".join(_spec_axes(self.spec)) or "dp"
        return f"{prefix}_grad_bucket{self.index}"


def _spec_axes(spec) -> Tuple[str, ...]:
    """Sorted axis names a spec tuple shards over (tuple entries like
    ("fsdp","tp") flattened); () for replicated."""
    axes = set()
    for ent in (spec or ()):
        for a in (ent if isinstance(ent, (tuple, list))
                  else (ent,) if ent else ()):
            axes.add(str(a))
    return tuple(sorted(axes))


def _norm_spec(spec) -> Tuple:
    """Hashable canonical form of a spec tuple (lists -> tuples, trailing
    Nones stripped) — the bucket group key next to dtype."""
    out = [tuple(ent) if isinstance(ent, list) else ent
           for ent in (spec or ())]
    while out and not out[-1]:
        out.pop()
    return tuple(out)


class OverlapPlan:
    """Buckets for one program version, indexed for the executor's trace
    loop. Cached like fusion plans, so it must stay stateless across
    traces — flush_range takes everything per-trace as arguments."""

    def __init__(self, buckets: List[Bucket]):
        self.buckets = buckets
        self.by_anchor: Dict[int, List[Bucket]] = {}
        for b in buckets:
            self.by_anchor.setdefault(b.anchor, []).append(b)
        self.anchors = sorted(self.by_anchor)

    @property
    def sites(self) -> List[str]:
        return [b.site for b in self.buckets]

    def flush_range(self, ctx, env, lo: int, hi: int):
        """Flush every bucket anchored in [lo, hi) — the op index span the
        trace loop just executed (a fused group advances several indices
        at once, so anchors inside the group flush after it)."""
        i = bisect.bisect_left(self.anchors, lo)
        while i < len(self.anchors) and self.anchors[i] < hi:
            for b in self.by_anchor[self.anchors[i]]:
                _flush(ctx, b, env)
            i += 1


_PLANS: Dict[Tuple[int, int], Tuple[Any, Optional[OverlapPlan]]] = {}


def plan(program) -> Optional[OverlapPlan]:
    """The program's bucket plan, or None when overlap is off / the
    program is not dp-mesh-tagged / it has no dense replicated parameter
    gradients. Cached per (id, version) like fusion.plan."""
    if not OVERLAP_OPT:
        return None
    mesh = getattr(program, "_mesh", None)
    if mesh is None or "dp" not in getattr(mesh, "axis_names", ()):
        return None
    key = (id(program), getattr(program, "_version", 0))
    hit = _PLANS.get(key)
    if hit is not None and hit[0] is program:
        return hit[1]
    if len(_PLANS) > 64:
        _PLANS.clear()
    p = _build(program)
    _PLANS[key] = (program, p)
    return p


def _dtype_nbytes(dtype: str) -> int:
    import numpy as np
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 4


def _grad_pairs(program) -> List[Tuple[str, str]]:
    """(param, grad) name pairs. append_backward records them on the
    program (`_grad_param_pairs`); older programs fall back to the
    grad_var_name convention against declared block vars."""
    pairs = getattr(program, "_grad_param_pairs", None)
    if pairs:
        return list(pairs)
    from ..framework.framework import grad_var_name
    block = program.global_block()
    out = []
    for p in block.all_parameters():
        if not getattr(p, "trainable", True):
            continue
        g = grad_var_name(p.name)
        if block.desc.has_var(g):
            out.append((p.name, g))
    return out


_CONSUMER_CACHE: Dict[Tuple[int, int], Dict[str, str]] = {}


def _grad_consumer_map(program) -> Dict[str, str]:
    """{grad var name -> consuming optimizer op type}, cached per
    (program, version): lets _flush label a SelectedRows gradient as
    handled-by-scatter-apply vs genuinely unsupported."""
    key = (id(program), getattr(program, "_version", 0))
    hit = _CONSUMER_CACHE.get(key)
    if hit is not None:
        return hit
    out: Dict[str, str] = {}
    try:
        ops = program.global_block().ops
    except AttributeError:   # synthetic test programs
        ops = ()
    for op_ in ops:
        ins = op_.desc.inputs
        if "Grad" in ins and "Param" in ins and ins["Grad"]:
            out[ins["Grad"][0]] = op_.type
    _CONSUMER_CACHE[key] = out
    while len(_CONSUMER_CACHE) > 64:
        _CONSUMER_CACHE.pop(next(iter(_CONSUMER_CACHE)))
    return out


def _build(program) -> Optional[OverlapPlan]:
    import numpy as np

    from . import planner as planner_mod

    block = program.global_block()
    pairs = _grad_pairs(program)
    if not pairs:
        return None
    # one pass over the block: where is each gradient last produced?
    last: Dict[str, int] = {}
    for i, op in enumerate(block.ops):
        for name in op.desc.output_arg_names():
            last[name] = i
    specs = getattr(program, "_param_shardings", {})
    mesh = getattr(program, "_mesh", None)
    mesh_axes = set(getattr(mesh, "axis_names", ()) or ())
    splan = getattr(program, "_sharding_plan", None)
    model_axes = planner_mod.model_axes(
        splan.layout if splan is not None else None)
    items = []  # (anchor, pname, gname, dtype, nbytes, spec)
    for pname, gname in pairs:
        anchor = last.get(gname)
        if anchor is None:
            continue  # grad never produced in this block (pruned)
        if pname in (getattr(program, "_sharded_tables", None) or {}):
            # row-sharded embedding table: the grad is SelectedRows by
            # construction and the scatter-apply optimizer consumes it —
            # handled by the sparse path, not an overlap miss
            count_fallback(program, "sharded_table_sparse_path")
            continue
        if gname in (getattr(program, "_sparse_grad_names", None) or ()):
            # is_sparse embedding grad (append_backward records these):
            # stays SelectedRows end-to-end on purpose
            count_fallback(program, "sparse_grad_handled")
            continue
        spec = _norm_spec(specs.get(pname))
        if spec:
            axes = set(_spec_axes(spec))
            if axes & model_axes:
                # genuinely model-parallel (tensor-sharded) grad: each
                # shard holds DIFFERENT values, there is no cross-dp sum
                # to schedule — GSPMD's per-param resharding stays
                count_fallback(program, "tp_sharded")
                continue
            if axes - mesh_axes:
                # spec names an axis this mesh doesn't have — can't pin
                # to it; keep the historical reason for dashboards
                count_fallback(program, "sharded_param")
                continue
            # dp/fsdp spec group: the grad IS a cross-dp sum; pinning it
            # to the param's spec is an eager reduce-scatter — bucketed
            # below per (dtype, spec) group
        try:
            var = block.var(gname) if block.desc.has_var(gname) \
                else block.var(pname)
            shape = tuple(var.shape or ())
            dtype = str(var.dtype)
        except Exception:
            count_fallback(program, "unknown_var")
            continue
        if any(d is None or d < 0 for d in shape):
            count_fallback(program, "dynamic_shape")
            continue
        nbytes = int(np.prod(shape, dtype=np.int64)) * _dtype_nbytes(dtype) \
            if shape else _dtype_nbytes(dtype)
        items.append((anchor, pname, gname, dtype, nbytes, spec))
    if not items:
        return None
    # readiness order: ascending last-producer index = the order backward
    # finishes gradients (reverse-topological over the forward graph)
    items.sort(key=lambda it: (it[0], it[2]))
    cap = _bucket_cap_bytes()
    buckets: List[Bucket] = []
    # (dtype, spec) group -> [params, grads, bytes, anchor]: grads only
    # bucket with grads that pin to the SAME sharding, so a replicated
    # fc bias never rides an fsdp weight's reduce-scatter bucket
    open_by_group: Dict[Tuple[str, Tuple], List[Any]] = {}

    def _close(group):
        acc = open_by_group.pop(group, None)
        if acc:
            dtype, spec = group
            buckets.append(Bucket(
                index=len(buckets), params=tuple(acc[0]),
                grads=tuple(acc[1]), dtype=dtype, bytes=acc[2],
                anchor=acc[3], spec=spec))

    for anchor, pname, gname, dtype, nbytes, spec in items:
        group = (dtype, spec)
        acc = open_by_group.get(group)
        if acc is not None and acc[2] + nbytes > cap:
            _close(group)
            acc = None
        if acc is None:
            acc = open_by_group[group] = [[], [], 0, anchor]
        acc[0].append(pname)
        acc[1].append(gname)
        acc[2] += nbytes
        acc[3] = max(acc[3], anchor)
    # deterministic close order for the stragglers: by group key
    for group in sorted(open_by_group, key=repr):
        _close(group)
    buckets.sort(key=lambda b: (b.anchor, b.index))
    # re-number in anchor order so site indices follow flush order
    buckets = [Bucket(index=i, params=b.params, grads=b.grads,
                      dtype=b.dtype, bytes=b.bytes, anchor=b.anchor,
                      spec=b.spec)
               for i, b in enumerate(buckets)]
    return OverlapPlan(buckets)


def _flush(ctx, bucket: Bucket, env: Dict[str, Any]):
    """Pin every dense member gradient to the bucket's spec-group
    sharding — replicated for the pure-dp group (eager all-reduce), the
    param's own dp/fsdp spec for a ZeRO group (eager reduce-scatter) —
    under the bucket's pd.coll scope. Pure annotation — the constrained
    value is the same value, so the trace stays bitwise identical; only
    WHERE the partitioner resolves the cross-device sum moves."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..ops.common import SelectedRowsVal
    from ._collectives import coll_scope

    program = ctx.program
    mesh = getattr(program, "_mesh", None)
    if mesh is None:
        return
    try:
        repl = NamedSharding(mesh, PartitionSpec(*bucket.spec))
    except (TypeError, ValueError):
        count_fallback(program, "constraint_failed")
        return
    emitted = 0
    with coll_scope(bucket.site):
        for gname in bucket.grads:
            v = env.get(gname)
            if v is None:
                count_fallback(program, "missing_grad")
                continue
            if isinstance(v, SelectedRowsVal):
                # sparse grads keep the per-param SelectedRows path —
                # densifying an embedding grad to bucket it is O(vocab).
                # Distinguish "the scatter-apply optimizer handles this"
                # (expected, not a miss) from a consumer that will
                # densify anyway (a genuine overlap+sparse gap).
                from ..ops import sparse_ops
                opt_t = _grad_consumer_map(program).get(gname)
                if opt_t in sparse_ops.SPARSE_APPLY_OPS \
                        and sparse_ops.sparse_apply_enabled():
                    count_fallback(program, "sparse_grad_handled")
                else:
                    count_fallback(program, "sparse_grad_unsupported")
                continue
            try:
                env[gname] = jax.lax.with_sharding_constraint(v, repl)
                emitted += 1
            except Exception:  # non-jax value / rank mismatch
                count_fallback(program, "constraint_failed")
    if emitted:
        from .. import telemetry
        telemetry.counter(
            "overlap_buckets_total",
            "gradient buckets flushed eagerly by the overlap pass "
            "(per trace)",
            labels=("program",)).labels(
            program=telemetry.program_label(program)).inc()


# --------------------------------------------------------------------------
# Layer 2: latency-hiding schedule plumbing (compiler options)
# --------------------------------------------------------------------------

# the async-collective + latency-hiding set for TPU backends; validated
# by _validate() before first use so a libtpu that drops one degrades to
# no options instead of failing every step
TPU_OVERLAP_OPTIONS: Dict[str, str] = {
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
    "xla_tpu_overlap_compute_collective_tc": "true",
}

_VALIDATED: Dict[Tuple[Tuple[str, str], ...], bool] = {}


def _parse_env_options(s: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip() or "true"
    return out


def _validate(opts: Dict[str, str]) -> bool:
    """Once per process per option set: compile-and-run a trivial jit with
    the options. XLA reports an unknown option as INVALID_ARGUMENT at the
    first call (not at jit() construction), and a jax without the
    compiler_options kwarg raises TypeError — both mean 'drop the set'."""
    key = tuple(sorted(opts.items()))
    hit = _VALIDATED.get(key)
    if hit is not None:
        return hit
    import jax
    import jax.numpy as jnp
    try:
        jax.jit(lambda a: a + 1, compiler_options=dict(opts))(
            jnp.zeros((), jnp.int32))
        ok = True
    except Exception:  # TypeError / XlaRuntimeError(INVALID_ARGUMENT)
        ok = False
    _VALIDATED[key] = ok
    return ok


def compiler_options(program=None) -> Optional[Dict[str, str]]:
    """The compiler_options dict for this program's jit compile, or None
    for a plain compile. None whenever there is nothing to overlap (off
    gate, no mesh) — keeping single-host compiles byte-identical to
    pre-overlap builds — or when the backend/options fail validation."""
    if not OVERLAP_OPT:
        return None
    if program is not None and getattr(program, "_mesh", None) is None:
        return None
    env = os.environ.get("PADDLE_TPU_OVERLAP_XLA_FLAGS")
    if env is not None:
        opts = _parse_env_options(env)
        if not opts:
            return None
    else:
        import jax
        if jax.default_backend() != "tpu":
            # CPU/GPU XLA rejects the TPU scheduler flags at first call;
            # the bucket layer still runs (it is backend-neutral)
            count_fallback(program, "platform")
            return None
        opts = dict(TPU_OVERLAP_OPTIONS)
    if not _validate(opts):
        count_fallback(program, "rejected_options")
        return None
    return opts


# --------------------------------------------------------------------------
# Layer 3: auto steps-per-call
# --------------------------------------------------------------------------

def choose_steps_per_call(python_overhead_ms: Optional[float] = None,
                          step_time_ms: Optional[float] = None,
                          feed_bytes_per_step: Optional[int] = None,
                          peak_bytes: Optional[int] = None,
                          budget_bytes: Optional[int] = None,
                          target_overhead_frac: float = 0.02,
                          lo: int = 1, hi: int = 64) -> int:
    """Pick the run_steps window K (`--steps-per-call auto`).

    Amortization: with K steps per dispatch the per-step Python cost is
    overhead/K, so K = ceil(overhead / (frac * step_time)) caps host
    dispatch at `target_overhead_frac` of device time. Memory: the
    stacked [K, B, ...] feed window grows linearly in K on top of the
    K=1 footprint, the same linear shape HeadroomModel fits for batch
    sizes — max_batch(budget) over (fixed = peak - one window,
    per_item = one window) bounds K to the HBM headroom. Missing
    measurements degrade gracefully: no timing signal means 'as large as
    memory allows', no memory signal means the amortization value alone,
    neither means `hi`. Result is always clamped to [lo, hi]."""
    lo = max(1, int(lo))
    hi = max(lo, int(hi))
    k = hi
    if python_overhead_ms and step_time_ms and step_time_ms > 0 \
            and target_overhead_frac > 0:
        need = python_overhead_ms / (target_overhead_frac * step_time_ms)
        k = min(k, max(lo, int(math.ceil(need))))
    if feed_bytes_per_step and budget_bytes:
        from ..memory import HeadroomModel
        model = HeadroomModel(
            fixed_bytes=max(0.0, float(peak_bytes or 0)
                            - float(feed_bytes_per_step)),
            per_item_bytes=float(feed_bytes_per_step))
        k_mem = model.max_batch(int(budget_bytes))
        if k_mem is not None:
            k = min(k, max(lo, k_mem))
    return max(lo, min(k, hi))
