"""Named-axis sharding planner: one data x fsdp x tp vocabulary (ISSUE 15).

The reference scales by REWRITING the graph for a parameter-server
topology (distribute_transpiler splitting dense vars and tables across
pservers, PAPER.md §distributed). The TPU-native equivalent never
rewrites an op: every parameter gets a PartitionSpec over a named
`data x fsdp x tp` mesh and XLA's GSPMD partitioner does the rest. This
module is the single place those specs come from — subsuming the three
disjoint vocabularies that grew before it (embedding.py's table specs,
tensor_parallel.py's column/row helpers, the dp special cases in
parallel/__init__.py):

1. **Role classification** (`classify_params`) — walk the ProgramDesc
   and name each parameter's job from the ops that consume it: a
   `lookup_table` W is an `embedding`; a `mul` weight whose output
   reaches `scaled_dot_product_attention` is `attn_qkv` while one whose
   input CAME from attention is `attn_out`; a weight feeding an
   activation is `ffn_up` and one fed BY an activation is `ffn_down`;
   the projection into the softmax/cross-entropy tail is the `lm_head`;
   conv Filters, norm Scale/Bias and rank-1 biases round out the set.
   The walk sees THROUGH shape/elementwise plumbing (TRANSPARENT_OPS)
   and ignores `_grad`/optimizer ops, so the same rules classify a
   transformer block and a DLRM tower.

2. **Role -> spec** (`SpecLayout.role_spec`) — the canonical Megatron +
   ZeRO algebra over named axes (SNIPPETS.md [2]): embeddings shard rows
   over fsdp x tp; qkv/ffn-up/lm-head are column-parallel (fsdp on the
   contraction dim, tp on the output dim); attn-out/ffn-down are
   row-parallel (tp on the contraction dim — the all-reduce pairs with
   the column-parallel all-gather); conv filters and generic dense
   weights ZeRO-shard dim 0 over fsdp; norm/bias stay replicated. Axes
   the mesh lacks drop out (`filter_axes`) and axes that do not divide a
   dim degrade per-axis with a counted
   `planner_fallback_total{program,reason}` — one layout serves
   1-device tests and dp=2,fsdp=2,tp=2 pods.

3. **`plan(program, mesh)`** — writes the result through the EXISTING
   channels, never a fourth vocabulary: embedding roles go through
   `embedding.shard_table` (so the sparse scatter-apply path and
   `_sharded_tables` bookkeeping engage), everything else through
   `tensor_parallel.shard_parameter`; feeds batch-shard over
   (data, fsdp) via `_feed_shardings`; optimizer accumulators follow
   their parameter through `embedding.resolve_state_spec` (generalized
   past tables for exactly this). The returned `Plan` carries per-param
   per-shard byte predictions that `validate_plan_bytes` cross-checks
   against `parallel.per_shard_param_bytes` to <= 1% — a hard test
   failure on drift, because a silent byte mismatch means the planner
   and the executor disagree about what one device holds.

Composes with: run_steps carry shardings (the executor pins state
outputs to the planned specs), overlap.py (buckets dp/fsdp grads per
spec group, counts `tp_sharded` for model-parallel ones),
analysis/preflight.py (validates planned specs before first compile)
and tools/check_registry.py's `check_planner_roles` lint (every role
producible, every rule op registered, embedding.py in agreement).

Env knobs: `PADDLE_TPU_MESH="dp=2,fsdp=2,tp=2"` sizes the mesh for
`mesh_from_env()` (examples, scaling_bench SCALE_MODEL=lm).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "SpecLayout", "ParamPlan", "Plan", "classify_params", "plan",
    "mesh_from_env", "model_axes", "validate_plan_bytes",
    "OP_INPUT_ROLES", "TRANSPARENT_OPS", "ATTENTION_OPS", "HEAD_OPS",
    "ROLES", "WALK_ROLES", "SPEC_ROLES", "MATMUL_OPS", "count_fallback",
]


# --------------------------------------------------------------------------
# Role tables (tools/check_registry.check_planner_roles lints every op
# name here against ops/registry.py — a typo never raises, the rule just
# silently stops matching)
# --------------------------------------------------------------------------

# (op_type, input slot) -> role, for parameters whose consuming op alone
# decides the role. Biases are handled structurally (rank-1 'Y' of an
# elementwise_add), not by table.
OP_INPUT_ROLES: Dict[Tuple[str, str], str] = {
    ("lookup_table", "W"): "embedding",
    ("conv2d", "Filter"): "conv_filter",
    ("depthwise_conv2d", "Filter"): "conv_filter",
    ("conv3d", "Filter"): "conv_filter",
    ("conv2d_transpose", "Filter"): "conv_filter",
    ("layer_norm", "Scale"): "norm",
    ("layer_norm", "Bias"): "norm",
    ("batch_norm", "Scale"): "norm",
    ("batch_norm", "Bias"): "norm",
}

# ops the matmul-weight walk sees through: pure shape/elementwise
# plumbing between a projection and the op that gives it meaning
TRANSPARENT_OPS = frozenset({
    "reshape", "transpose", "elementwise_add", "dropout", "scale",
    "cast", "concat", "split", "squeeze", "unsqueeze", "sum",
})

# attention sink/source: a weight projecting INTO one of these is qkv,
# a weight consuming its output is the output projection
ATTENTION_OPS = frozenset({"scaled_dot_product_attention"})

# loss-head sinks: a weight projecting into the softmax tail is the
# model head (lm_head for the transformer, the classifier head for DLRM)
HEAD_OPS = frozenset({"softmax_with_cross_entropy", "softmax",
                      "cross_entropy"})

# weight-bearing matmul ops whose "Y" operand triggers the graph walk
MATMUL_OPS = frozenset({"mul", "matmul"})

# roles the graph walk (as opposed to the direct table) can produce
WALK_ROLES = frozenset({"attn_qkv", "attn_out", "ffn_up", "ffn_down",
                        "lm_head", "bias", "dense"})

# the full role vocabulary the classifier can produce
ROLES = frozenset(OP_INPUT_ROLES.values()) | WALK_ROLES

# roles SpecLayout.role_spec distinguishes — check_registry's
# check_planner_roles pins this == ROLES in both directions (a spec-table
# role no classifier rule produces is dead; a classifier role the spec
# table doesn't know falls into the replicated default silently)
SPEC_ROLES = frozenset({
    "embedding", "attn_qkv", "ffn_up", "lm_head", "attn_out", "ffn_down",
    "conv_filter", "dense", "norm", "bias",
})


def count_fallback(program, reason: str, amount: int = 1):
    """planner_fallback_total{program,reason} — the per-reason telemetry
    shape shared with fusion/overlap/pallas: every spec the planner had
    to degrade (indivisible dim, unknown role kept replicated) is
    counted, never silent."""
    from .. import telemetry
    telemetry.counter(
        "planner_fallback_total",
        "parameters whose planned sharding was degraded or skipped by "
        "reason (named-axis sharding planner)",
        labels=("program", "reason")).labels(
        program=telemetry.program_label(program), reason=reason).inc(amount)


# --------------------------------------------------------------------------
# SpecLayout: role -> PartitionSpec entries over named axes
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class SpecLayout:
    """Role map from parameter roles to dim-0-first spec tuples over
    named mesh axes (SNIPPETS.md [2]): embeddings shard their row (vocab)
    dim over fsdp x tp and replicate the feature dim; projections follow
    the Megatron column/row algebra with ZeRO-style fsdp on the other
    dim; norm/bias replicate. Axes absent from the actual mesh are
    dropped at application time (`filter_axes`), so one layout serves
    1-device tests and fsdp x tp pods alike."""

    data_axis: str = "dp"
    fsdp_axis: str = "fsdp"
    tensor_axis: str = "tp"

    def embeddings(self) -> Tuple:
        return ((self.fsdp_axis, self.tensor_axis), None)

    def ffn_column(self) -> Tuple:
        """Column-parallel [in, out]: tp splits output features (each
        device computes a slice of the activation), fsdp ZeRO-shards the
        contraction dim (all-gathered on use, grad reduce-scattered)."""
        return (self.fsdp_axis, self.tensor_axis)

    def ffn_row(self) -> Tuple:
        """Row-parallel [in, out]: tp splits the contraction dim so the
        partial products all-reduce once, pairing with the column
        projection before it; fsdp ZeRO-shards the output dim."""
        return (self.tensor_axis, self.fsdp_axis)

    def role_spec(self, role: str, ndim: int) -> Tuple:
        """Canonical spec tuple for `role` at rank `ndim` (pre-filter,
        pre-divisibility: plan() degrades it against the real mesh and
        shapes). Unknown roles replicate — the safe default."""
        if role == "embedding":
            spec = self.embeddings()
        elif role in ("attn_qkv", "ffn_up", "lm_head"):
            spec = self.ffn_column()
        elif role in ("attn_out", "ffn_down"):
            spec = self.ffn_row()
        elif role in ("conv_filter", "dense"):
            spec = (self.fsdp_axis,)
        else:  # norm / bias / anything unknown: replicated
            spec = ()
        spec = tuple(spec)[:ndim]
        return spec + (None,) * (ndim - len(spec))

    def filter_axes(self, spec: Tuple, mesh) -> Tuple:
        """Drop axes the mesh does not have; collapse empty entries to
        None so the spec stays valid on smaller meshes."""
        have = set(getattr(mesh, "axis_names", ()) or ())
        out = []
        for ent in spec:
            axes = (tuple(ent) if isinstance(ent, (tuple, list))
                    else (ent,) if ent else ())
            axes = tuple(a for a in axes if a in have)
            out.append(axes[0] if len(axes) == 1 else (axes or None))
        return tuple(out)

    def batch_spec(self, mesh) -> Tuple:
        """Dim-0 entry for feed batch sharding: the global batch splits
        over data x fsdp (FSDP is data parallelism with sharded state,
        so both axes carry examples)."""
        return self.filter_axes(((self.data_axis, self.fsdp_axis),),
                                mesh)


def model_axes(layout: Optional[SpecLayout] = None) -> frozenset:
    """Axes that make a gradient genuinely model-parallel (different
    VALUES per shard, not a sharded copy of the same sum): overlap.py
    skips those with the counted `tp_sharded` reason instead of
    bucketing them."""
    if layout is None:
        # "mp" is tensor_parallel.py's historical axis name
        return frozenset({"tp", "mp"})
    return frozenset({layout.tensor_axis, "mp"})


# --------------------------------------------------------------------------
# Role classification: walk the ProgramDesc
# --------------------------------------------------------------------------

def _is_optimizer_op(op) -> bool:
    ins = op.desc.inputs
    return "Param" in ins and "Grad" in ins


def _forward_ops(program):
    """(index, op) for forward ops only: the classifier reads the model
    structure, and grad/optimizer ops would double-count every consumer
    (lookup_table_grad also takes W, sgd takes Param, ...)."""
    for i, op in enumerate(program.global_block().ops):
        t = op.type
        if t.endswith("_grad") or t.startswith("fused_sparse_"):
            continue
        if _is_optimizer_op(op):
            continue
        yield i, op


def _walk_forward(start: str, consumers, depth: int = 12):
    """Op types reachable from var `start` through TRANSPARENT_OPS —
    the sinks that give a projection output its meaning. Bounded depth:
    residual chains in an N-layer net would otherwise drag every later
    block's sinks into every earlier projection."""
    sinks: List[str] = []
    seen = set()
    frontier = [start]
    for _ in range(depth):
        nxt: List[str] = []
        for name in frontier:
            for (t, _slot, outs) in consumers.get(name, ()):
                if t in TRANSPARENT_OPS:
                    for o in outs:
                        if o not in seen:
                            seen.add(o)
                            nxt.append(o)
                else:
                    sinks.append(t)
        if not nxt:
            break
        frontier = nxt
    return sinks


def _walk_backward(start: str, producers, depth: int = 12):
    """Op types that (transitively through TRANSPARENT_OPS) produced var
    `start` — what a projection's INPUT came from."""
    sources: List[str] = []
    seen = set()
    frontier = [start]
    for _ in range(depth):
        nxt: List[str] = []
        for name in frontier:
            prod = producers.get(name)
            if prod is None:
                continue
            t, ins = prod
            if t in TRANSPARENT_OPS:
                for i_ in ins:
                    if i_ not in seen:
                        seen.add(i_)
                        nxt.append(i_)
            else:
                sources.append(t)
        if not nxt:
            break
        frontier = nxt
    return sources


def classify_params(program) -> Dict[str, str]:
    """{param name -> role} for every parameter of the global block, by
    walking the forward ops that consume it. Precedence per param:

      1. a direct OP_INPUT_ROLES hit (lookup_table W, conv Filter,
         norm Scale/Bias) wins outright;
      2. a rank-1 'Y' operand of elementwise_add is a bias;
      3. a MATMUL_OPS 'Y' weight walks the graph: output reaching
         ATTENTION_OPS -> attn_qkv; input produced by ATTENTION_OPS ->
         attn_out; output reaching HEAD_OPS -> lm_head; input produced
         by an activation -> ffn_down; output feeding an activation ->
         ffn_up;
      4. everything else is generic `dense` (ZeRO dim-0 sharding).
    """
    from ..ops import fusion

    block = program.global_block()
    params = {p.name: p for p in block.all_parameters()}
    if not params:
        return {}
    act_ops = set(fusion.ACT_OPS) | {"gelu", "relu", "tanh", "sigmoid",
                                     "swish"}

    # single pass: who consumes / produces each var, forward ops only
    consumers: Dict[str, List] = {}
    producers: Dict[str, Tuple] = {}
    uses: Dict[str, List] = {n: [] for n in params}
    for _i, op in _forward_ops(program):
        outs = list(op.desc.output_arg_names())
        all_ins = list(op.desc.input_arg_names())
        for slot, names in op.desc.inputs.items():
            for n in names:
                if n in params:
                    uses[n].append((op.type, slot, op))
                consumers.setdefault(n, []).append((op.type, slot, outs))
        for o in outs:
            producers[o] = (op.type, all_ins)

    roles: Dict[str, str] = {}
    for pname, p in params.items():
        ndim = len(p.shape or ())
        role = None
        for (t, slot, op) in uses[pname]:
            role = OP_INPUT_ROLES.get((t, slot))
            if role:
                break
        if role is None and ndim == 1:
            # rank-1 'Y' of a broadcast add = a layer bias
            if any(t == "elementwise_add" and slot == "Y"
                   for (t, slot, _op) in uses[pname]):
                role = "bias"
        if role is None:
            for (t, slot, op) in uses[pname]:
                if t not in MATMUL_OPS or slot != "Y":
                    continue
                outs = list(op.desc.output_arg_names())
                ins = [n for n in op.desc.input_arg_names()
                       if n != pname]
                sinks = []
                for o in outs:
                    sinks.extend(_walk_forward(o, consumers))
                sources = []
                for i_ in ins:
                    sources.extend(_walk_backward(i_, producers))
                if any(s in ATTENTION_OPS for s in sinks):
                    role = "attn_qkv"
                elif any(s in ATTENTION_OPS for s in sources):
                    role = "attn_out"
                elif any(s in HEAD_OPS for s in sinks):
                    role = "lm_head"
                elif any(s in act_ops for s in sources):
                    role = "ffn_down"
                elif any(s in act_ops for s in sinks):
                    role = "ffn_up"
                if role:
                    break
        roles[pname] = role or "dense"
    return roles


# --------------------------------------------------------------------------
# The plan
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class ParamPlan:
    """One parameter's resolved sharding: `spec` is the final (filtered,
    divisibility-degraded) entry tuple written to `_param_shardings`;
    `factor` the device count splitting it; `per_shard_bytes` the ceil
    division XLA's padded shards occupy."""
    name: str
    role: str
    spec: Tuple
    shape: Tuple[int, ...]
    bytes: int
    per_shard_bytes: int
    factor: int
    notes: Tuple[str, ...] = ()


@dataclass
class Plan:
    """plan()'s result: per-param decisions + the mesh/layout they were
    made against. `predicted` per-shard byte totals are the numbers
    validate_plan_bytes pins against parallel.per_shard_param_bytes."""
    params: Dict[str, ParamPlan]
    mesh_axes: Tuple[str, ...]
    layout: SpecLayout
    feed_specs: Dict[str, Tuple] = field(default_factory=dict)

    @property
    def model_axes(self) -> frozenset:
        return model_axes(self.layout)

    def by_role(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for pp in self.params.values():
            out.setdefault(pp.role, []).append(pp.name)
        return {r: sorted(ns) for r, ns in out.items()}

    @property
    def total_bytes(self) -> int:
        return sum(pp.bytes for pp in self.params.values())

    @property
    def per_shard_bytes(self) -> int:
        return sum(pp.per_shard_bytes for pp in self.params.values())

    def to_dict(self) -> Dict:
        return {
            "mesh_axes": list(self.mesh_axes),
            "roles": {n: pp.role for n, pp in sorted(self.params.items())},
            "specs": {n: list(pp.spec)
                      for n, pp in sorted(self.params.items())},
            "total_bytes": self.total_bytes,
            "per_shard_bytes": self.per_shard_bytes,
        }


def _dtype_itemsize(var) -> int:
    try:
        return np.dtype(str(var.dtype)).itemsize
    except TypeError:
        return 4


def _degrade(spec: Tuple, shape, axis_sizes, notes: List[str],
             pname: str) -> Tuple:
    """Drop axes that do not divide their dim (tp first inside tuple
    entries, since dropping fsdp loses more memory savings). GSPMD would
    otherwise pad — legal but byte-accounting poison — and an
    indivisible NAMED axis is always a planning bug worth a counter."""
    out = []
    for d, ent in enumerate(spec):
        axes = list(ent if isinstance(ent, (tuple, list))
                    else (ent,) if ent else ())
        dim = shape[d] if d < len(shape) else -1
        while axes:
            factor = 1
            for a in axes:
                factor *= int(axis_sizes.get(a, 1))
            if dim == -1 or factor <= 1 or dim % factor == 0:
                break
            dropped = axes.pop()   # tp sits last in tuple entries
            notes.append(f"{pname}: dim {d} ({dim}) not divisible by "
                         f"{factor} — dropped axis '{dropped}'")
        out.append(axes[0] if len(axes) == 1 else (tuple(axes) or None))
    return tuple(out)


def _feed_vars(program) -> List[str]:
    """Graph inputs: non-persistable vars consumed but never produced by
    any op — the feed surface plan() batch-shards."""
    block = program.global_block()
    produced = set()
    consumed = set()
    for op in block.ops:
        produced.update(op.desc.output_arg_names())
        consumed.update(op.desc.input_arg_names())
    out = []
    for n in sorted(consumed - produced):
        if not block.has_var(n):
            continue
        v = block.var(n)
        if getattr(v, "persistable", False):
            continue
        if not (v.shape or ()):
            continue
        out.append(n)
    return out


def plan(program, mesh=None, layout: Optional[SpecLayout] = None,
         feeds: Optional[Sequence[str]] = None,
         shard_feeds: bool = True) -> Plan:
    """Classify every parameter, resolve each role's spec against the
    mesh, and write the result through the existing channels:
    `embedding.shard_table` for embedding roles (sparse path +
    `_sharded_tables` bookkeeping), `tensor_parallel.shard_parameter`
    for everything else, `_feed_shardings` batch specs over
    (data, fsdp) for the feed surface. Tags the program with the mesh
    when given one, stores the Plan at `program._sharding_plan`, and
    bumps `_version` once so compiled-step and pass caches invalidate.

    Idempotent per (program, mesh): re-planning overwrites the same
    channels with the same values.
    """
    from . import embedding as embedding_mod
    from . import tensor_parallel as tp_mod

    if mesh is not None:
        program._mesh = mesh
    else:
        mesh = getattr(program, "_mesh", None)
    if mesh is None:
        raise ValueError("planner.plan needs a mesh: pass one or tag the "
                         "program (program._mesh = make_mesh(...))")
    layout = layout or SpecLayout()
    axis_sizes = dict(getattr(mesh, "shape", None) or {})
    block = program.global_block()
    roles = classify_params(program)

    params: Dict[str, ParamPlan] = {}
    for p in block.all_parameters():
        pname = p.name
        role = roles.get(pname, "dense")
        shape = tuple(int(d) for d in (p.shape or ()))
        ndim = len(shape)
        notes: List[str] = []
        spec = layout.filter_axes(layout.role_spec(role, ndim), mesh)
        spec = _degrade(spec, shape, axis_sizes, notes, pname)
        for _ in notes:
            count_fallback(program, "indivisible")
        factor = 1
        for ent in spec:
            for a in (ent if isinstance(ent, (tuple, list))
                      else (ent,) if ent else ()):
                factor *= int(axis_sizes.get(a, 1))
        nbytes = int(np.prod(shape, dtype=np.int64)) * _dtype_itemsize(p) \
            if shape else 0
        per_shard = -(-nbytes // factor) if factor > 1 else nbytes
        if any(ent for ent in spec):
            if role == "embedding":
                # the sparse lookup/scatter path + _sharded_tables
                # bookkeeping hang off shard_table, not the raw spec
                ent = spec[0]
                axes = tuple(ent) if isinstance(ent, (tuple, list)) \
                    else (ent,)
                embedding_mod.shard_table(program, pname, axes)
            else:
                tp_mod.shard_parameter(program, pname, spec)
        else:
            # replicated by plan: drop any stale annotation so a re-plan
            # onto a smaller mesh does not leave dead axis names behind
            specs = getattr(program, "_param_shardings", None)
            if specs and pname in specs:
                del specs[pname]
            if role not in ("norm", "bias"):
                count_fallback(program, "replicated")
        params[pname] = ParamPlan(
            name=pname, role=role, spec=spec, shape=shape, bytes=nbytes,
            per_shard_bytes=per_shard, factor=factor, notes=tuple(notes))

    feed_specs: Dict[str, Tuple] = {}
    if shard_feeds:
        batch = layout.batch_spec(mesh)
        if batch and batch[0]:
            from . import shard_feed
            names = list(feeds) if feeds is not None \
                else _feed_vars(program)
            for n in names:
                v = block.var(n) if block.has_var(n) else None
                ndim = len(v.shape or ()) if v is not None else 1
                spec = batch + (None,) * (ndim - 1)
                shard_feed(program, n, spec)
                feed_specs[n] = spec

    p = Plan(params=params, mesh_axes=tuple(mesh.axis_names),
             layout=layout, feed_specs=feed_specs)
    program._sharding_plan = p
    program._version = getattr(program, "_version", 0) + 1
    return p


# --------------------------------------------------------------------------
# Validation + env plumbing
# --------------------------------------------------------------------------

def validate_plan_bytes(program, scope=None, tol: float = 0.01
                        ) -> Dict[str, Dict]:
    """Cross-check the plan's predicted per-shard bytes against
    parallel.per_shard_param_bytes (the accounting the bench columns and
    memory.classify ride). Returns {param: {predicted, accounted}} for
    every parameter BOTH sides measured; raises AssertionError on any
    relative mismatch > tol — a hard failure, because divergence means
    the planner and the executor disagree about per-device HBM."""
    from . import per_shard_param_bytes

    p: Optional[Plan] = getattr(program, "_sharding_plan", None)
    if p is None:
        raise ValueError("program has no _sharding_plan — call "
                         "planner.plan first")
    acct = per_shard_param_bytes(program, scope)["params"]
    out: Dict[str, Dict] = {}
    for name, pp in p.params.items():
        a = acct.get(name)
        if a is None or not a.get("bytes"):
            continue  # not materialized in this scope
        out[name] = {"predicted": pp.per_shard_bytes,
                     "accounted": a["per_device"]}
        err = abs(pp.per_shard_bytes - a["per_device"]) / max(
            a["per_device"], 1)
        assert err <= tol, (
            f"planner byte accounting diverged for '{name}': predicted "
            f"{pp.per_shard_bytes} per-shard bytes, "
            f"per_shard_param_bytes says {a['per_device']} "
            f"(rel err {err:.3f} > {tol})")
    return out


def mesh_from_env(default: str = "", devices=None):
    """Mesh from `PADDLE_TPU_MESH="dp=2,fsdp=2,tp=2"` (or `default` when
    the env var is unset; empty default means all devices on 'dp').
    Axis order in the string IS the mesh axis order; sizes must multiply
    to <= the available device count."""
    import jax

    from .mesh import make_mesh

    raw = os.environ.get("PADDLE_TPU_MESH", default)
    devices = list(devices if devices is not None else jax.devices())
    if not raw.strip():
        return make_mesh((len(devices),), ("dp",), devices=devices)
    shape: List[int] = []
    names: List[str] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        try:
            size = int(v)
        except ValueError:
            raise ValueError(f"PADDLE_TPU_MESH entry '{part}' is not "
                             f"axis=<int>")
        if size < 1:
            raise ValueError(f"PADDLE_TPU_MESH axis '{k}' has size "
                             f"{size} < 1")
        names.append(k.strip())
        shape.append(size)
    n = 1
    for s in shape:
        n *= s
    if n > len(devices):
        raise ValueError(f"PADDLE_TPU_MESH '{raw}' needs {n} devices, "
                         f"only {len(devices)} available")
    return make_mesh(tuple(shape), tuple(names), devices=devices[:n])
