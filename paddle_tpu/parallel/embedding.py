"""Sharded sparse embedding tables: fsdp-partitioned rows (ISSUE 10).

The reference serves recommender-scale tables from parameter servers
(distributed lookup_table, reference: distribute_transpiler splitting
tables row-wise across pservers). The TPU-native translation is GSPMD:
annotate the table's row dim with mesh axes (SNIPPETS.md [2]
`SpecLayout.embeddings()` — replicated over data, sharded over fsdp×tp)
and let the partitioner turn `lookup_table`'s gather into local gathers
plus one cross-shard combine (`pd.coll.emb_lookup`). This module owns
the annotation side:

  * `SpecLayout` — role map from parameter roles to PartitionSpecs over
    named axes, the planner vocabulary the ROADMAP names.
  * `shard_table` / `shard_embeddings` — row-shard one table / every
    `lookup_table` W in a program; records `program._sharded_tables` so
    the executor, fusion, overlap, and memory layers can tell a sharded
    *table* (sparse path handles it) from a generically sharded param.
  * `resolve_state_spec` — optimizer accumulators (`<param>_<acc>_<n>`,
    optimizer.py naming) of a sharded table inherit the table's row
    sharding, so a 1M×64 adam table's moments shard with it instead of
    replicating.
  * `per_shard_table_bytes` / `state_shard_factor` — per-device HBM
    accounting for tables + their optimizer state (memory.py breakdown,
    bench evidence columns).

Shard-axis selection: `PADDLE_TPU_EMB_SHARD_AXIS` (default "fsdp") names
the mesh axis (comma-separated for multi-axis) used when a caller does
not pass one explicitly.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple, Union

# The role→spec vocabulary lives in planner.py since ISSUE 15 (one
# data × fsdp × tp vocabulary); re-exported here because PR 10 callers
# import it from this module.
from .planner import SpecLayout

__all__ = [
    "SpecLayout", "default_shard_axes", "shard_table", "shard_embeddings",
    "sharded_tables", "table_accumulators", "resolve_state_spec",
    "state_shard_factor", "per_shard_table_bytes",
]

Axes = Union[str, Sequence[str]]


def default_shard_axes() -> Tuple[str, ...]:
    """PADDLE_TPU_EMB_SHARD_AXIS (comma-separated), default ("fsdp",)."""
    raw = os.environ.get("PADDLE_TPU_EMB_SHARD_AXIS", "fsdp")
    return tuple(a.strip() for a in raw.split(",") if a.strip())


def shard_table(program, param_name: str, axis: Optional[Axes] = None):
    """Row-shard one embedding table over mesh axis/axes (default from
    PADDLE_TPU_EMB_SHARD_AXIS). Writes the same `_param_shardings`
    annotation tensor_parallel.shard_parameter uses — the executor's
    in_shardings and the sparse lookup/apply kernels read it — and marks
    the param in `program._sharded_tables` so fallback dashboards can
    label it "handled by sparse path" rather than "sharded param"."""
    from . import tensor_parallel as tp_mod

    axes = (tuple(axis) if isinstance(axis, (tuple, list))
            else (axis,) if axis else default_shard_axes())
    ndim = None
    blk = program.global_block()
    if blk.has_var(param_name):
        shp = blk.var(param_name).shape
        ndim = len(shp) if shp is not None else None
    first = axes[0] if len(axes) == 1 else tuple(axes)
    spec = (first,) + (None,) * ((ndim or 2) - 1)
    # one vocabulary: the spec write (and its _version bump) goes through
    # tensor_parallel.shard_parameter; only the sparse-path marker is ours
    tp_mod.shard_parameter(program, param_name, spec)
    tables = getattr(program, "_sharded_tables", None)
    if tables is None:
        tables = program._sharded_tables = {}
    tables[param_name] = axes
    return program


def sharded_tables(program) -> Dict[str, Tuple[str, ...]]:
    """{table param name -> row-shard axes} recorded by shard_table."""
    return dict(getattr(program, "_sharded_tables", None) or {})


def shard_embeddings(program, axis: Optional[Axes] = None,
                     mesh=None, layout: Optional[SpecLayout] = None
                     ) -> List[str]:
    """Row-shard every `lookup_table` W parameter in the program. With a
    `layout`, the spec comes from `layout.embeddings()` filtered to the
    mesh's axes; otherwise `axis`/PADDLE_TPU_EMB_SHARD_AXIS. Returns the
    table names annotated."""
    mesh = mesh if mesh is not None else getattr(program, "_mesh", None)
    if layout is not None and mesh is not None:
        ent = layout.filter_axes(layout.embeddings(), mesh)[0]
        axes = (tuple(ent) if isinstance(ent, (tuple, list))
                else (ent,) if ent else ())
        axis = axes or axis
    blk = program.global_block()
    done: List[str] = []
    for op_ in blk.ops:
        if op_.type != "lookup_table":
            continue
        wnames = op_.input("W")
        if not wnames:
            continue
        wname = wnames[0]
        if wname in done or not blk.has_var(wname):
            continue
        shard_table(program, wname, axis)
        done.append(wname)
    return done


def table_accumulators(program, pname: str) -> List[str]:
    """Optimizer accumulators shadowing table `pname`'s shape, by the
    optimizer.py naming convention (`unique_name.generate(f"{param}_
    {acc}")`) plus a shape-equality check that keeps scalar state like
    beta-pow vars (shape [1]) and unlucky name collisions out. Shared
    vocabulary for the sharding resolver below (a sharded table's
    moments shard with it) and for parallel/emb_cache.py (a cached
    table's moments cache — and flush — with it)."""
    blk = program.global_block()
    if not blk.has_var(pname):
        return []
    pshape = tuple(blk.var(pname).shape or ())
    if not pshape:
        return []
    out = []
    for vname in list(blk.vars):
        if vname == pname or not vname.startswith(pname + "_"):
            continue
        if not blk.has_var(vname):
            continue
        if tuple(blk.var(vname).shape or ()) == pshape:
            out.append(vname)
    return sorted(out)


# accumulator→param maps are O(vars × sharded params) to build, and the
# executor asks per state var per compile — cache per (program, version)
_ACCUM_CACHE: Dict[Tuple[int, int], Dict[str, str]] = {}


def _accum_of(program, name: str) -> Optional[str]:
    """Sharded param whose optimizer accumulator `name` is, or None
    (table_accumulators membership over every spec'd param — since the
    planner, ANY sharded parameter's accumulators follow it, not just
    `_sharded_tables` entries)."""
    sharded = set(getattr(program, "_sharded_tables", None) or ())
    sharded.update(getattr(program, "_param_shardings", None) or ())
    if not sharded:
        return None
    key = (id(program), getattr(program, "_version", 0))
    cached = _ACCUM_CACHE.get(key)
    if cached is None:
        if len(_ACCUM_CACHE) > 64:
            _ACCUM_CACHE.clear()
        cached = {}
        for pname in sorted(sharded):
            for aname in table_accumulators(program, pname):
                cached.setdefault(aname, pname)
        _ACCUM_CACHE[key] = cached
    return cached.get(name)


def resolve_state_spec(program, name: str):
    """PartitionSpec entry tuple for a persistable state var: the
    parameter's own `_param_shardings` annotation, or — for an optimizer
    accumulator shadowing a sharded table's shape — the table's row
    sharding. The executor's in_shardings/donated-state pinning and
    memory.py's per-shard accounting both route through here so moments
    and velocity live sharded next to their table."""
    specs = getattr(program, "_param_shardings", {}) or {}
    if name in specs:
        return specs[name]
    pname = _accum_of(program, name)
    return specs.get(pname) if pname else None


def state_shard_factor(program, name: str) -> int:
    """How many devices split state var `name` under the program's mesh
    (1 = replicated). Counts mesh axis sizes over every sharded dim of
    the resolved spec, handling tuple entries like ("fsdp", "tp")."""
    spec = resolve_state_spec(program, name)
    mesh = getattr(program, "_mesh", None)
    if not spec or mesh is None:
        return 1
    sizes = dict(mesh.shape)
    f = 1
    for ent in spec:
        axes = (tuple(ent) if isinstance(ent, (tuple, list))
                else (ent,) if ent else ())
        for a in axes:
            f *= int(sizes.get(a, 1))
    return f


def per_shard_table_bytes(program, scope=None) -> Dict:
    """Per-device HBM for each sharded table and its optimizer state:
    {tables: {name: {rows, dim, bytes, per_shard_bytes, opt_state_bytes,
    opt_state_per_shard_bytes, factor}}, total_bytes,
    total_per_shard_bytes}. The bench `embedding` family emits these as
    evidence columns (acceptance: per-shard ≈ total/devices at 8
    devices). Bytes come from live scope vars when materialized, else
    from the block's static shapes."""
    from .. import executor as executor_mod
    from .. import memory as memory_mod
    import numpy as np

    scope = scope if scope is not None else executor_mod.global_scope()
    blk = program.global_block()
    out: Dict[str, Dict] = {}
    total = total_ps = 0

    def _nbytes(name: str) -> int:
        v = scope.find_var(name)
        b = memory_mod.nbytes_of(v)
        if b:
            return int(b)
        if blk.has_var(name):
            var = blk.var(name)
            shp = tuple(var.shape or ())
            if shp and all(int(s) > 0 for s in shp):
                itemsize = np.dtype(str(var.dtype)).itemsize \
                    if var.dtype else 4
                n = 1
                for s in shp:
                    n *= int(s)
                return n * itemsize
        return 0

    for pname in sharded_tables(program):
        if not blk.has_var(pname):
            continue
        shp = tuple(blk.var(pname).shape or ())
        factor = state_shard_factor(program, pname)
        b = _nbytes(pname)
        opt_b = opt_ps = 0
        for vname in list(blk.vars):
            if vname != pname and _accum_of(program, vname) == pname:
                ab = _nbytes(vname)
                opt_b += ab
                opt_ps += -(-ab // state_shard_factor(program, vname))
        per_shard = -(-b // factor) if factor > 1 else b
        out[pname] = {
            "rows": int(shp[0]) if shp else 0,
            "dim": int(shp[1]) if len(shp) > 1 else 0,
            "bytes": int(b), "per_shard_bytes": int(per_shard),
            "opt_state_bytes": int(opt_b),
            "opt_state_per_shard_bytes": int(opt_ps),
            "factor": int(factor),
        }
        total += b + opt_b
        total_ps += per_shard + opt_ps
    return {"tables": out, "total_bytes": int(total),
            "total_per_shard_bytes": int(total_ps)}
