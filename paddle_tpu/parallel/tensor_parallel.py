"""Tensor (model) parallelism via GSPMD parameter sharding.

TPU-native successor of the reference's coarse model parallelism
(reference: gserver/gradientmachines/ParallelNeuralNetwork.h — whole layers
pinned to devices; ModelConfig per-layer `device` attr). Instead of moving
layers, parameters carry `jax.sharding.PartitionSpec` annotations: the
executor passes them as in_shardings and XLA GSPMD partitions every matmul
touching them, inserting the all-gather/reduce-scatter collectives over ICI
(the Megatron column/row-parallel pattern falls out of annotating the fc
weight's output or input dimension).

API:
    mesh = make_mesh((dp, tp), ("dp", "mp"))
    DistributeTranspiler().transpile(trainers=..., mesh=mesh)
    shard_parameter(program, "fc_0.w_0", (None, "mp"))   # column-parallel
    shard_parameter(program, "fc_1.w_0", ("mp", None))   # row-parallel
    # or the sweep helper:
    shard_fc_params(program, axis="mp")
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

__all__ = ["shard_parameter", "param_shardings", "shard_fc_params",
           "shard_all_params_zero", "expected_collectives"]


def _specs(program) -> Dict[str, Tuple]:
    if not hasattr(program, "_param_shardings"):
        program._param_shardings = {}
    return program._param_shardings


def shard_parameter(program, param_name: str, spec: Sequence[Optional[str]]):
    """Annotate one parameter with a PartitionSpec (dims -> mesh axis or
    None). The executor turns this into an in_sharding for the jitted
    train step; XLA propagates it through every consumer. This is THE
    spec write path — planner.plan and embedding.shard_table both route
    through here — so the _version bump that invalidates compiled-step
    and overlap-plan caches lives here and nowhere else."""
    specs = _specs(program)
    spec = tuple(spec)
    if specs.get(param_name) != spec:
        specs[param_name] = spec
        program._version = getattr(program, "_version", 0) + 1
    return program


def param_shardings(program) -> Dict[str, Tuple]:
    return dict(getattr(program, "_param_shardings", {}))


def shard_fc_params(program, axis: str = "mp", min_dim: int = 2):
    """Column-shard every 2-D fc/mul weight over `axis` (Megatron
    column-parallel): weight [in, out] splits on out, so each device holds
    a slice of output features and XLA all-gathers activations where
    needed. Biases of matching size shard too."""
    sharded_cols = set()
    for p in program.global_block().all_parameters():
        shape = p.shape
        if shape is not None and len(shape) == 2 and shape[1] >= min_dim:
            shard_parameter(program, p.name, (None, axis))
            sharded_cols.add(shape[1])
    # 1-D biases whose length matches a sharded output dim
    for p in program.global_block().all_parameters():
        shape = p.shape
        if shape is not None and len(shape) == 1 and shape[0] in sharded_cols:
            shard_parameter(program, p.name, (axis,))
    return program


def expected_collectives(program) -> Dict[str, str]:
    """{param_name: predicted GSPMD collective pattern} for every annotated
    parameter — the Megatron algebra in words. Tensor-parallel collectives
    are partitioner-inserted, so no framework line carries a pd.coll
    scope for them; the fleet CLI prints these predictions next to the
    trace's "(gspmd)" rows so an unattributed all-gather still names its
    probable source parameter."""
    out: Dict[str, str] = {}
    for name, spec in param_shardings(program).items():
        spec = tuple(spec)
        ndim = len(spec)
        axes = [a for a in spec if a]
        if not axes:
            continue
        if ndim >= 2 and spec[-1]:
            out[name] = ("column-parallel ({0}): activation all-gather on "
                         "use, grad reduce-scatter".format(spec[-1]))
        elif ndim >= 2 and spec[0]:
            out[name] = ("row-parallel ({0}): output all-reduce"
                         .format(spec[0]))
        elif ndim == 1:
            out[name] = ("sharded bias ({0}): gathers with its layer"
                         .format(axes[0]))
        else:
            out[name] = ("zero-sharded ({0}): param all-gather on use, "
                         "grad reduce-scatter".format(axes[0]))
    return out


def shard_all_params_zero(program, axis: str = "dp", min_size: int = 1024):
    """ZeRO-ish parameter sharding: every parameter (above min_size
    elements) shards its leading dim over the data axis; XLA all-gathers on
    use and reduce-scatters gradients — the GSPMD stand-in for the
    reference pserver's block-sharded parameter storage
    (distribute_transpiler.py:92 split_dense_variable)."""
    import numpy as np
    for p in program.global_block().all_parameters():
        shape = p.shape
        if shape and all(d is not None for d in shape) and \
                int(np.prod(shape)) >= min_size:
            shard_parameter(program, p.name,
                            (axis,) + (None,) * (len(shape) - 1))
    return program
