"""Elastic data dispatch: the Go master's task queue without etcd
(reference go/master/service.go — partition :106, GetTask :368,
TaskFinished :411, TaskFailed :455 with failureMax, timeout requeue :341,
snapshot each mutation :207, recover :166).

The reference runs a leased etcd singleton; here the queue state is ONE
JSON snapshot in a shared directory, every mutation happens under an
exclusive flock and replaces the snapshot atomically. Any trainer process
mutates the queue directly — the "master" is the file, so master failover
is free (recover = read the snapshot), and trainer counts can change
between or during passes: a crashed trainer's leased tasks time out and
requeue to whoever asks next. That is the EDL data-plane contract
(trainers stateless, work re-dispatched) on a shared filesystem instead
of etcd; compute elasticity still means restart-from-checkpoint with a
new mesh (README scope notes).

CORRECTNESS REQUIREMENT: the queue directory's filesystem must honor
flock ACROSS the participating hosts — true for a local disk shared by
same-host processes and for NFSv4 (or NFSv3/Lustre mounted with flock
enabled), NOT for NFSv3/Lustre default "localflock" mounts, where two
hosts could both win the lock and lose mutations. Multi-host clusters on
such mounts should put the queue on the job's coordinator host and export
it properly, exactly where the reference put etcd.

todo/pending(leased)/done/failed states mirror service.go's taskQueues
{Todo, Pending, Done, Failed}.
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["TaskQueue", "elastic_reader"]


class TaskQueue:
    def __init__(self, dirname: str, timeout_s: float = 60.0,
                 failure_max: int = 3, clock: Callable[[], float] = None):
        self.dirname = dirname
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.clock = clock or time.time
        os.makedirs(dirname, exist_ok=True)
        self._snap = os.path.join(dirname, "queue.json")
        self._lock = os.path.join(dirname, "queue.lock")

    # --- locked snapshot mutation (service.go:207 snapshot per mutation) --
    def _mutate(self, fn, readonly_ok: bool = False):
        with open(self._lock, "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            state = self._read()
            expired = False
            if state is not None:
                expired = self._requeue_expired(state)
            out = fn(state)
            state = out[0] if isinstance(out, tuple) else out
            if state is not None and not (readonly_ok and not expired):
                tmp = self._snap + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(state, f)
                os.replace(tmp, self._snap)
            return out[1] if isinstance(out, tuple) else None

    def _read(self) -> Optional[dict]:
        if not os.path.exists(self._snap):
            return None
        with open(self._snap) as f:
            return json.load(f)

    def _requeue_expired(self, state) -> bool:
        """Timeout requeue (service.go:341 checkTimeoutFunc); returns
        whether anything changed."""
        now = self.clock()
        expired = [tid for tid, lease in state["pending"].items()
                   if lease["deadline"] <= now]
        for tid in expired:
            del state["pending"][tid]
            self._fail_task(state, tid)
        return bool(expired)

    def _fail_task(self, state, tid):
        """Failure budget (service.go:313 processFailedTask)."""
        state["failures"][tid] = state["failures"].get(tid, 0) + 1
        if state["failures"][tid] >= self.failure_max:
            state["failed"].append(tid)        # discarded for this pass
        else:
            state["todo"].append(tid)

    # --- public API (service.go RPC surface) ------------------------------
    def partition(self, items: List[Any], chunks_per_task: int = 1):
        """Idempotent pass initialization (service.go:106 partition): the
        first caller splits `items` into tasks; later callers are no-ops,
        so every trainer can race to call it."""
        def fn(state):
            if state is not None and state.get("epoch", 0) > 0:
                return state
            tasks = {}
            order = []
            for i in range(0, len(items), chunks_per_task):
                tid = str(len(order))
                tasks[tid] = items[i:i + chunks_per_task]
                order.append(tid)
            return {"epoch": 1, "tasks": tasks, "todo": order,
                    "pending": {}, "done": [], "failed": [],
                    "failures": {}}
        self._mutate(fn)

    def get_task(self, worker: str = "") -> Optional[Tuple[str, List[Any]]]:
        """Lease the next task (service.go:368 GetTask); None when the
        pass is drained (todo empty and nothing pending)."""
        def fn(state):
            assert state is not None, "partition() first"
            if not state["todo"]:
                return state, None
            tid = state["todo"].pop(0)
            state["pending"][tid] = {
                "worker": worker, "deadline": self.clock() + self.timeout_s}
            return state, (tid, state["tasks"][tid])
        return self._mutate(fn)

    def task_finished(self, task_id: str):
        """(service.go:411 TaskFinished)"""
        def fn(state):
            if task_id in state["pending"]:
                del state["pending"][task_id]
                state["done"].append(task_id)
            return state
        self._mutate(fn)

    def task_failed(self, task_id: str):
        """Explicit failure report (service.go:455 TaskFailed)."""
        def fn(state):
            if task_id in state["pending"]:
                del state["pending"][task_id]
                self._fail_task(state, task_id)
            return state
        self._mutate(fn)

    def pass_done(self) -> bool:
        # read-mostly: expired-lease requeue is the only mutation that can
        # matter here; skip the snapshot rewrite when nothing expired
        # (idle workers poll this in the drain-wait loop)
        def fn(state):
            return state, (state is not None and not state["todo"]
                           and not state["pending"])
        return self._mutate(fn, readonly_ok=True)

    def reset_pass(self):
        """Start the next pass over the same tasks (the reference's
        NewPass / todo re-fill)."""
        def fn(state):
            assert state is not None
            state["epoch"] += 1
            state["todo"] = sorted(state["tasks"], key=int)
            state["pending"] = {}
            state["done"] = []
            state["failed"] = []
            state["failures"] = {}
            return state
        self._mutate(fn)

    def stats(self) -> dict:
        def fn(state):
            if state is None:
                return state, {}
            return state, {k: len(state[k])
                           for k in ("todo", "pending", "done", "failed")}
        return self._mutate(fn, readonly_ok=True)


def elastic_reader(queue: TaskQueue, chunk_fetch: Callable[[Any], List],
                   worker: str = ""):
    """Sample stream driven by the task queue (go/master/client.go:244
    NextRecord): lease a task, yield its chunks' samples, mark finished;
    repeat until the pass drains. A trainer that dies mid-task simply
    never calls task_finished — the lease times out and the task requeues
    to another trainer (at-least-once, exactly the Go master's
    guarantee)."""
    def reader():
        while True:
            leased = queue.get_task(worker)
            if leased is None:
                if queue.pass_done():
                    return
                time.sleep(0.05)       # wait out other workers' leases
                continue
            tid, chunks = leased
            for chunk in chunks:
                for sample in chunk_fetch(chunk):
                    yield sample
            queue.task_finished(tid)
    return reader
