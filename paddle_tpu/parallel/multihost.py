"""Multi-host bring-up + fault-tolerant data sharding + checkpoint-restart.

TPU-native replacement for the reference's distributed control plane
(reference: go/master/service.go:89-455 — the Go master partitions recordio
chunks into a task queue so any number of trainers can consume them, with
snapshot/recover; go/pserver/service.go:120-227,346 — parameter shards
checkpointed with metadata for restart; trainer env plumbing
python/paddle/fluid/tests/book/test_fit_a_line.py:71-96 PADDLE_INIT_*).

On TPU the data plane is jax.distributed + GSPMD: every host runs the same
SPMD program, `initialize()` wires the processes into one JAX runtime
(collectives ride ICI/DCN; no pservers), `shard_reader` statically
partitions the sample stream by process the way the master's task queue
does dynamically (elastic trainer counts are descoped — see README), and
save/load_checkpoint give the kill-and-resume loop: persistables + a
step-counter metadata file, written atomically, recovered on restart.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Callable, Iterable, Optional

__all__ = ["initialize", "shard_reader", "CheckpointableReader",
           "save_checkpoint", "load_checkpoint", "latest_checkpoint",
           "is_save_leader", "allgather_bytes"]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Bring up the multi-controller JAX runtime (one process per host).

    Reads the reference's env conventions when args are omitted:
    PADDLE_COORDINATOR (host:port of process 0), PADDLE_TRAINERS,
    PADDLE_TRAINER_ID (reference trainer env: test_fit_a_line.py:83-90).
    No-op in single-process mode (nothing to coordinate)."""
    import jax
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_COORDINATOR")
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if num_processes <= 1 or coordinator_address is None:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    # telemetry snapshots label by host; export the id the same way the
    # reference trainer env did so _host_index() needs no backend query
    os.environ.setdefault("PADDLE_TRAINER_ID", str(process_id))
    from .. import telemetry
    telemetry.counter("multihost_initialize_total",
                      "jax.distributed bring-ups in this process").inc()
    telemetry.gauge("multihost_processes",
                    "process count of the multi-controller runtime") \
        .set(num_processes)
    return True


def allgather_bytes(payload: bytes) -> list:
    """One bytes payload per process, gathered in process order (see
    parallel/_collectives.py). The transport for fleet-wide telemetry
    reduction: each host contributes its serialized metrics snapshot and
    every host gets all of them back — one collective, no sidecar server,
    the DCN analogue of scraping every pserver."""
    from . import _collectives
    return _collectives.process_allgather_bytes(payload)


def shard_reader(reader: Callable[[], Iterable], num_shards=None,
                 shard_id=None):
    """Partition a sample stream across processes: process i consumes every
    num_shards-th sample starting at i. The static-sharding equivalent of
    the Go master's chunk task queue (go/master/service.go:106 partition) —
    every host sees a disjoint 1/N of the data each pass."""
    import jax
    if num_shards is None:
        num_shards = jax.process_count()
    if shard_id is None:
        shard_id = jax.process_index()

    def sharded():
        for i, sample in enumerate(reader()):
            if i % num_shards == shard_id:
                yield sample

    return sharded


class CheckpointableReader:
    """Sample stream with a checkpointable position: (pass_id, offset) ride
    the checkpoint metadata, so a restart resumes mid-pass — consumed
    samples are neither replayed nor lost. This is the Go master's
    task-queue snapshot/recover semantics (go/master/service.go:207
    snapshot each mutation, :166 recover) collapsed onto the static-shard
    reader: position IS the queue state when shards are deterministic.

    Use as a reader factory: each call yields the remainder of the current
    pass, then advances to the next pass starting at offset 0. Determinism
    requirement: the wrapped factory must yield the same stream each pass
    (shuffle via a pass_id-seeded RNG, e.g. reader.shuffle with a fixed
    seed — same requirement the reference's chunk queue puts on recordio
    files).

    Prefetching caveat: position advances at yield time, so samples sitting
    in a prefetch buffer (e.g. DoubleBufferedFeeder) count as consumed. A
    checkpoint taken then would LOSE those in-flight samples on restart —
    pass the buffer depth as `in_flight` to state()/save_checkpoint so the
    recorded position backs up over them (restart re-reads them instead;
    replaying an in-flight sample is safe, dropping it is not)."""

    def __init__(self, reader_factory: Callable[[], Iterable]):
        self.reader_factory = reader_factory
        self.pass_id = 0
        self.offset = 0

    def state(self, in_flight: int = 0) -> dict:
        off = max(0, self.offset - int(in_flight))
        return {"reader_pass": self.pass_id, "reader_offset": off}

    def restore(self, state: dict):
        self.pass_id = int(state.get("reader_pass", 0))
        self.offset = int(state.get("reader_offset", 0))

    def __call__(self):
        skip = self.offset
        for i, sample in enumerate(self.reader_factory()):
            if i < skip:
                continue
            # position advances BEFORE the consumer processes the sample:
            # a checkpoint taken after a step records that step's samples
            # as consumed (the reference marks a task done only on
            # TaskFinished; here the executor step and the checkpoint are
            # atomic w.r.t. each other because checkpoints happen between
            # steps)
            self.offset = i + 1
            yield sample
        self.pass_id += 1
        self.offset = 0


# --- checkpoint-restart -------------------------------------------------------

_META = "checkpoint_meta.json"


def is_save_leader() -> bool:
    """True on the one process elected to write checkpoints. The reference
    elects ONE trainer to save (go/master/service.go:481 RequestSaveModel);
    under SPMD every process holds identical (or completing) param state,
    so process 0 is the natural lease-free leader."""
    import jax
    return jax.process_index() == 0


def save_checkpoint(executor, dirname: str, step: int, main_program=None,
                    extra_meta: Optional[dict] = None, reader=None,
                    reader_in_flight: int = 0, leader_only: bool = True):
    """Persistables + step metadata, written atomically (temp file + rename)
    so a crash mid-write never corrupts the latest checkpoint — the
    md5+meta discipline of the Go pserver checkpoints
    (go/pserver/service.go:120-203). Pass a CheckpointableReader as
    `reader` to capture the data-stream position too (mid-pass resume);
    `reader_in_flight` = number of samples sitting in prefetch buffers
    between the reader and the training step (they get re-read on
    restart rather than lost).

    In multi-process SPMD only the elected leader writes the params + meta
    (reference RequestSaveModel, go/master/service.go:481: every process
    would otherwise race on the same directory) — but each process's
    reader position is process-local state, so EVERY process persists its
    own into a distinct per-process file (no race) that load_checkpoint
    restores by process index. Returns True when this process wrote the
    main checkpoint. leader_only=False restores the old
    every-process-writes behavior for process-local dirnames."""
    import jax
    t0 = time.perf_counter()
    os.makedirs(dirname, exist_ok=True)
    rstate = None
    if reader is not None:
        # snapshot the reader position ONCE and reuse it for both the
        # per-process file and the leader's meta below — a prefetch thread
        # advancing the reader between two state() calls would otherwise
        # record two different stream positions for the same step
        # (ADVICE r4)
        rstate = reader.state(in_flight=reader_in_flight)
        # per-process reader position: distinct filename per process, so
        # non-leaders persist their shard's stream position too
        fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".rdr.tmp")
        with os.fdopen(fd, "w") as f:
            json.dump({"step": step, **rstate}, f)
        os.replace(tmp, os.path.join(
            dirname, _reader_state_file(jax.process_index())))
    if leader_only and not is_save_leader():
        _checkpoint_done("save", step, t0)
        return False
    from .. import io as io_mod
    ckpt_dir = os.path.join(dirname, f"step_{step}")
    os.makedirs(ckpt_dir, exist_ok=True)
    io_mod.save_persistables(executor, ckpt_dir, main_program=main_program)
    meta = {"step": step, **(extra_meta or {})}
    if rstate is not None:
        meta.update(rstate)
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".meta.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(dirname, _META))
    from .. import telemetry
    telemetry.counter("checkpoint_saves_total",
                      "checkpoints written by this process").inc()
    telemetry.gauge("checkpoint_last_step",
                    "step of the newest checkpoint written").set(step)
    _checkpoint_done("save", step, t0)
    return True


def _checkpoint_done(op: str, step, t0: float):
    """Duration histogram + 'checkpoint' event marker: the goodput ledger
    (fleet.goodput_report) prices checkpoint badput from these instead of
    guessing from the checkpoint_bytes gauge."""
    from .. import telemetry
    dt = time.perf_counter() - t0
    telemetry.histogram(f"checkpoint_{op}_seconds",
                        f"wall seconds per checkpoint {op}").observe(dt)
    telemetry.log_event("checkpoint", op=op, step=step, seconds=dt)


def _reader_state_file(process_index: int) -> str:
    return f"reader_state_p{process_index}.json"


def latest_checkpoint(dirname: str) -> Optional[dict]:
    """Metadata of the newest complete checkpoint, or None."""
    path = os.path.join(dirname, _META)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        meta = json.load(f)
    ckpt_dir = os.path.join(dirname, f"step_{meta['step']}")
    return meta if os.path.isdir(ckpt_dir) else None


def load_checkpoint(executor, dirname: str, main_program=None,
                    reader=None) -> Optional[dict]:
    """Restore persistables from the newest checkpoint; returns its metadata
    (with 'step') or None when no checkpoint exists — the trainer resumes
    at meta['step'] + 1 (master recover parity, go/master/service.go:166).
    With `reader` (a CheckpointableReader), the data-stream position is
    restored too — from THIS process's per-process state file when present
    (multi-process runs: each shard's position is its own), falling back
    to the leader-written meta fields."""
    import jax
    from .. import io as io_mod
    meta = latest_checkpoint(dirname)
    if meta is None:
        return None
    t0 = time.perf_counter()
    ckpt_dir = os.path.join(dirname, f"step_{meta['step']}")
    io_mod.load_persistables(executor, ckpt_dir, main_program=main_program)
    _checkpoint_done("load", meta["step"], t0)
    if reader is not None:
        rpath = os.path.join(dirname,
                             _reader_state_file(jax.process_index()))
        rstate = None
        if os.path.exists(rpath):
            with open(rpath) as f:
                cand = json.load(f)
            # only trust a position recorded at this checkpoint's step: a
            # stale file (e.g. from a later, incomplete save) must not
            # skew the resume point
            if cand.get("step") == meta["step"]:
                rstate = cand
        if rstate is None and is_save_leader():
            # the meta's reader fields ARE the leader's own position
            rstate = meta
        if rstate is not None:
            reader.restore(rstate)
        # a non-leader with no consistent per-process file keeps the fresh
        # (pass-start) position: replaying its shard is at-least-once
        # safe, whereas adopting the LEADER's offset could skip samples
    return meta
