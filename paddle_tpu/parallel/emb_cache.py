"""Beyond-HBM embedding tables: device hot-row cache over a host-DRAM
authoritative store (ISSUE 14 tentpole).

The reference framework keeps recommender-scale tables on parameter
servers and caches hot rows near the worker (distributed lookup_table,
PAPER.md pserver machinery). The TPU-native translation: the
*authoritative* table lives in host DRAM as a numpy slab per table (and
per optimizer accumulator), while the traced program sees only a
fixed-size device-resident **cache slab** of `cache_rows` hot rows.
Feed-time id→slot remapping keeps every traced shape static as the
cache churns, so:

  * `lookup_table` does its normal static-shape `jnp.take` — against
    the [cache_rows, dim] slab with slot indices instead of row ids;
  * the PR 10 scatter-apply optimizers (`sgd/momentum/adam`
    SelectedRows kernels, ops/sparse_ops.py) run unmodified: the
    gradient's rows are already slot indices, and `.at[rows].set(...,
    mode="drop")` scatters into the slab.

Touched-row numerics are bitwise (sgd/momentum) / tolerance (lazy
adam) equal to the all-HBM path: remapping is elementwise, so
`merge_selected_rows`'s per-id segment sums see the same addends in
the same order, and the `*_dense` update math runs on identical row
values (tests/test_emb_cache.py pins this end to end, across a
checkpoint save/restore).

Residency protocol (`EmbCache`):

  * `prepare_feed(feed)` — make every id of the feed resident
    (`_ensure`), remap ids→slots, pin the window's slots against
    eviction while the dispatched step is in flight, and mark them
    dirty (the optimizer will scatter into them).
  * `prefetch(uniq)` — the overlapped half: a background thread
    resolves window i+1's unique-id union (from
    `DoubleBufferedFeeder.next_window(..., sparse_slots=[...])`)
    against the id→slot map and stages only the missing rows (plus
    accumulator rows) into victim slots while window i computes.
    Victims are chosen LRU-with-frequency-tiebreak; dirty victims
    flush back to the host slab off the critical path.
  * `flush()` — write every dirty slot back to host DRAM. io.py calls
    this before checkpoint save and substitutes the host slab for the
    cache slab, so a crash after save never loses touched rows
    (crash-consistency: host DRAM is authoritative, the checkpoint is
    taken from it after the flush barrier).

Gating: `PADDLE_TPU_EMB_CACHE=0` kill-switch; per-table opt-in via
`layers.embedding(..., cache_rows=N)` or `enable(program,
budget_bytes=...)` (budget sized e.g. from `memory.HeadroomModel`
headroom minus the window feed buffer — `budget_from_headroom`).

Telemetry: `emb_cache_hit_rate{table}`,
`emb_cache_prefetch_overlap_fraction`, `emb_cache_flush_bytes_total`,
`emb_cache_evictions_total{policy}` plus hit/miss counters.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "CACHE_AWARE_OPS", "cache_enabled", "request_cache", "requested_rows",
    "rows_for_budget", "budget_from_headroom", "enable", "enable_serving",
    "active_cache", "EmbCache",
]

#: Ops allowed to reference a cached table. Everything here either
#: gathers with the (already slot-remapped) feed ids or scatters a
#: SelectedRows update whose rows ARE those slot indices — no op in this
#: set ever interprets a table index it did not receive from the feed,
#: so the remap is complete. tools/check_registry.check_emb_cache pins
#: this set against sparse_ops.SPARSE_APPLY_OPS and
#: executor._SPARSE_AWARE_OPS; enable() refuses a table referenced by
#: any op outside it (that op would index with global row ids and read
#: garbage slots).
def _cache_aware_ops() -> frozenset:
    from ..ops import sparse_ops
    return frozenset(
        {"lookup_table", "lookup_table_grad"}
        | set(sparse_ops.SPARSE_APPLY_OPS)
        | {"fused_sparse_" + t for t in sparse_ops.SPARSE_APPLY_OPS})


CACHE_AWARE_OPS: frozenset = _cache_aware_ops()

_EVICT_POLICY = "lru_freq"


def cache_enabled() -> bool:
    """PADDLE_TPU_EMB_CACHE kill-switch (default on; the feature is
    already opt-in per table, the env gates it off for bisection)."""
    return os.environ.get("PADDLE_TPU_EMB_CACHE", "1") != "0"


def request_cache(program, param_name: str, cache_rows: int):
    """Record a per-table cache request (layers.embedding(cache_rows=N)
    routes here); `enable(program)` activates every recorded table."""
    req = getattr(program, "_emb_cache_rows", None)
    if req is None:
        req = program._emb_cache_rows = {}
    req[param_name] = int(cache_rows)
    return program


def requested_rows(program) -> Dict[str, int]:
    return dict(getattr(program, "_emb_cache_rows", None) or {})


def active_cache(program) -> Optional["EmbCache"]:
    """The program's live EmbCache, or None (also None when the
    kill-switch is set after enable — remapping garbage is worse than
    serving the slab as-is, so the gate is read at enable time only)."""
    return getattr(program, "_emb_cache", None)


def rows_for_budget(budget_bytes: int, dim: int, itemsize: int,
                    n_state: int) -> int:
    """cache_rows affordable under `budget_bytes` of device memory: one
    row costs dim*itemsize for the param plus the same for every cached
    accumulator slab (adam: x3)."""
    row_bytes = max(1, int(dim) * int(itemsize) * max(1, int(n_state)))
    return max(0, int(budget_bytes) // row_bytes)


def budget_from_headroom(model, batch: int, limit_bytes: Optional[int] = None,
                         window_feed_bytes: int = 0) -> int:
    """Device bytes left for cache slabs: HBM limit minus the
    HeadroomModel's predicted peak at `batch` minus the window feed
    buffer (run_steps stages K batches on device). The ISSUE-mandated
    sizing hook: fit the model from two static analyses, then size the
    cache from what's genuinely left."""
    from .. import memory as memory_mod
    limit = int(limit_bytes) if limit_bytes else memory_mod.default_budget()
    return int(model.headroom(limit, batch)) - int(window_feed_bytes)


class _CachedTable:
    """Residency state for one table: host-DRAM authoritative slabs for
    the param + each row-shaped optimizer accumulator, the id→slot /
    slot→id maps, and the LRU-with-frequency eviction bookkeeping."""

    def __init__(self, name: str, rows: int, dim: int, cache_rows: int,
                 state_names: Sequence[str], ids_inputs: Sequence[str]):
        self.name = name
        self.rows = int(rows)
        self.dim = int(dim)
        self.cache_rows = int(cache_rows)
        self.state_names = list(state_names)     # param first
        self.ids_inputs = list(ids_inputs)       # feed var names
        self.host: Dict[str, np.ndarray] = {}
        # id→slot is a flat int32 map (8..4 bytes/row host DRAM — dwarfed
        # by the dim*4-byte slabs it indexes); -1 = not resident
        self.id2slot = np.full(self.rows, -1, dtype=np.int32)
        self.slot2id = np.full(self.cache_rows, -1, dtype=np.int64)
        # ids ever counted by prepare_feed: splits misses into compulsory
        # (first touch — no policy could have avoided it) vs capacity
        # (the row was here once and got evicted) so hit-rate gates can
        # judge the eviction policy, not the workload's novelty rate
        self.ever = np.zeros(self.rows, dtype=bool)
        self.freq = np.zeros(self.cache_rows, dtype=np.int64)
        self.last_used = np.zeros(self.cache_rows, dtype=np.int64)
        self.dirty = np.zeros(self.cache_rows, dtype=bool)
        self.tick = 0
        # NOTE: no pin on the in-flight window's slots. Evicting one is
        # safe — the dirty flush reads the slab through get_state, which
        # holds the post-update array; np.asarray on it blocks until the
        # dispatched window lands, so the flushed values are current.
        # (uniq ids, ids the prefetch staged as misses) of the last
        # prefetch — the consuming prepare_feed counts those as misses
        # rather than re-deriving them (they are resident by then)
        self.prefetch_pending = None
        # occurrence-weighted (per lookup, not per unique id): the zipf
        # head's mass is the whole point of a hot-row cache, and a
        # unique-id denominator would erase it
        self.hits = 0
        self.misses = 0
        self.miss_compulsory = 0
        self.evictions = 0


class _PrefetchHandle:
    """Join handle for one background prefetch. `wait()` measures how
    much of the prefetch's wall time was hidden behind the caller's
    compute: the fraction of [start, end] that elapsed before the
    caller reached wait() (the caller dispatched the window first, so
    time before wait-entry ran under the in-flight step)."""

    def __init__(self, cache: "EmbCache", work: Callable[[], None]):
        self._cache = cache
        self._t0 = 0.0
        self._t1 = 0.0
        self._err: Optional[BaseException] = None

        def run():
            self._t0 = time.perf_counter()
            try:
                work()
            except BaseException as e:   # re-raised at wait()
                self._err = e
            self._t1 = time.perf_counter()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="pd-emb-prefetch")
        self._thread.start()

    def wait(self):
        t_enter = time.perf_counter()
        self._thread.join()
        dur = max(self._t1 - self._t0, 0.0)
        overlapped = min(max(min(self._t1, t_enter) - self._t0, 0.0), dur)
        self._cache._note_overlap(dur, overlapped)
        if self._err is not None:
            raise self._err
        return self


class EmbCache:
    """Hot-row cache over host-DRAM authoritative embedding tables.

    `get_state`/`set_state` abstract where the device slabs live: the
    executor binding reads/writes the Scope; the serving binding
    (read_only=True) the engine's resident state dict. All map/slab
    mutation happens under one lock — prepare_feed on the training
    thread and prefetch on its background thread interleave safely.
    """

    def __init__(self, program, tables: Sequence[_CachedTable],
                 get_state: Callable, set_state: Callable,
                 read_only: bool = False):
        self.program = program
        self.read_only = read_only
        self._tables: Dict[str, _CachedTable] = {t.name: t for t in tables}
        self._get_state = get_state
        self._set_state = set_state
        self._lock = threading.RLock()
        self._prefetch_seconds = 0.0
        self._overlap_seconds = 0.0
        self._flush_bytes = 0
        # feed id var name -> table name (one table may read several)
        self._ids_to_table: Dict[str, str] = {}
        for t in tables:
            for n in t.ids_inputs:
                self._ids_to_table[n] = t.name

    # --- introspection ------------------------------------------------------
    def tables(self) -> Dict[str, _CachedTable]:
        return dict(self._tables)

    def feed_id_names(self) -> List[str]:
        return sorted(self._ids_to_table)

    def owns(self, state_name: str) -> bool:
        return any(state_name in t.state_names
                   for t in self._tables.values())

    def hit_rate(self, table: Optional[str] = None) -> float:
        with self._lock:
            ts = ([self._tables[table]] if table
                  else list(self._tables.values()))
            h = sum(t.hits for t in ts)
            m = sum(t.misses for t in ts)
        return h / (h + m) if (h + m) else 0.0

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "hits": sum(t.hits for t in self._tables.values()),
                "misses": sum(t.misses for t in self._tables.values()),
                # first-touch misses: no eviction policy avoids these, so
                # policy gates subtract them from the miss denominator
                "compulsory_misses": sum(t.miss_compulsory
                                         for t in self._tables.values()),
                "hit_rate": self.hit_rate(),
                "evictions": sum(t.evictions
                                 for t in self._tables.values()),
                "flush_bytes": self._flush_bytes,
                "prefetch_seconds": self._prefetch_seconds,
                "overlap_seconds": self._overlap_seconds,
                "overlap_fraction": (
                    self._overlap_seconds / self._prefetch_seconds
                    if self._prefetch_seconds > 0 else 0.0),
                "tables": {
                    n: {"cache_rows": t.cache_rows, "rows": t.rows,
                        "resident": int((t.slot2id >= 0).sum()),
                        "hits": t.hits, "misses": t.misses,
                        "compulsory_misses": t.miss_compulsory,
                        "evictions": t.evictions}
                    for n, t in self._tables.items()},
            }

    # --- residency core -----------------------------------------------------
    def _slab(self, name: str):
        v = self._get_state(name)
        if v is None:
            raise RuntimeError(
                f"emb_cache: device slab '{name}' vanished from its "
                f"store — was the scope cleared after enable()?")
        return v.array() if hasattr(v, "array") else v

    def _ensure(self, t: _CachedTable, uniq: np.ndarray, count: bool,
                counts: Optional[np.ndarray] = None,
                premissed: Optional[np.ndarray] = None):
        """Make every id of sorted-unique `uniq` resident. Caller holds
        the lock. `counts` are per-id occurrence counts aligned to
        `uniq` (default 1 each) — hit/miss telemetry and the eviction
        frequency signal are lookup-weighted. `count=False` skips
        telemetry entirely (prefetch staging: the consuming prepare_feed
        counts instead, passing the prefetch's miss set as `premissed`
        so rows the prefetch staged still count as misses — hidden
        latency is still transfer traffic)."""
        import jax.numpy as jnp

        if uniq.size and (int(uniq[0]) < 0 or int(uniq[-1]) >= t.rows):
            bad = uniq[(uniq < 0) | (uniq >= t.rows)]
            raise ValueError(
                f"emb_cache: ids out of range for table '{t.name}' "
                f"([0, {t.rows})): {bad[:8].tolist()}")
        if counts is None:
            counts = np.ones(uniq.size, dtype=np.int64)
        slots = t.id2slot[uniq]
        miss_mask = slots < 0
        if count:
            cmask = (np.isin(uniq, premissed, assume_unique=True)
                     if premissed is not None else miss_mask)
            n_miss = int(counts[cmask].sum())
            n_hit = int(counts.sum()) - n_miss
            t.hits += n_hit
            t.misses += n_miss
            t.miss_compulsory += int(counts[cmask & ~t.ever[uniq]].sum())
            t.ever[uniq] = True
            self._record_rate(t, n_hit, n_miss)
        t.tick += 1
        hit_slots = slots[~miss_mask]
        t.freq[hit_slots] += counts[~miss_mask]
        t.last_used[hit_slots] = t.tick
        n_miss = int(miss_mask.sum())
        if n_miss == 0:
            return
        if int(uniq.size) > t.cache_rows:
            raise RuntimeError(
                f"emb_cache: one window touches {uniq.size} unique rows "
                f"of '{t.name}' but cache_rows={t.cache_rows} — every "
                f"scanned step runs against one slab, so the window "
                f"union must fit; raise cache_rows above the touched-row "
                f"bound or lower the batch size / window length")
        miss_ids = uniq[miss_mask]
        # only this request's own hit slots are off-limits (self-eviction
        # would unmap a row the remapped feed is about to index)
        blocked = np.zeros(t.cache_rows, dtype=bool)
        blocked[hit_slots] = True
        free = np.flatnonzero((t.slot2id < 0) & ~blocked)
        victims = free[:n_miss]
        need = n_miss - victims.size
        if need > 0:
            occ = np.flatnonzero((t.slot2id >= 0) & ~blocked)
            # LRU with frequency tiebreak: oldest last_used first, and
            # among equals the least-frequently-hit slot goes
            order = np.lexsort((t.freq[occ], t.last_used[occ]))
            evict = occ[order[:need]]
            self._evict(t, evict)
            victims = np.concatenate([victims, evict])
        t.slot2id[victims] = miss_ids
        t.id2slot[miss_ids] = victims.astype(np.int32)
        t.freq[victims] = counts[miss_mask]
        t.last_used[victims] = t.tick
        t.dirty[victims] = False
        jvict = jnp.asarray(victims)
        for name in t.state_names:
            cur = jnp.asarray(self._slab(name))
            staged = jnp.asarray(t.host[name][miss_ids])
            self._set_state(name, cur.at[jvict].set(staged))

    def _evict(self, t: _CachedTable, slots: np.ndarray):
        """Flush dirty victims to the host slab, then unmap. Runs off
        the critical path when reached from prefetch's thread."""
        old_ids = t.slot2id[slots]
        live = old_ids >= 0
        dirty = slots[live & t.dirty[slots]]
        if dirty.size and not self.read_only:
            ids = t.slot2id[dirty]
            flushed = 0
            for name in t.state_names:
                vals = np.asarray(self._slab(name)[dirty])
                t.host[name][ids] = vals
                flushed += vals.nbytes
            self._record_flush(flushed)
        t.id2slot[old_ids[live]] = -1
        t.slot2id[slots] = -1
        t.dirty[slots] = False
        t.evictions += int(slots.size)
        from .. import telemetry
        telemetry.counter(
            "emb_cache_evictions_total",
            "hot-row cache slots evicted, by victim-selection policy",
            labels=("policy",)).labels(policy=_EVICT_POLICY).inc(
                int(slots.size))

    # --- public protocol ----------------------------------------------------
    def prepare_feed(self, feed: Dict) -> Dict:
        """Ensure residency for every cached-table id in `feed` and
        return the feed with ids remapped to cache-slot indices. Works
        for per-step [B, ...] and window-stacked [K, B, ...] id arrays
        alike (the union of the whole window must be resident at once —
        the scanned steps all run against one slab)."""
        present = [n for n in self._ids_to_table if n in feed]
        if not present:
            return feed
        out = dict(feed)
        with self._lock:
            by_table: Dict[str, List[str]] = {}
            for n in present:
                by_table.setdefault(self._ids_to_table[n], []).append(n)
            for tname, names in sorted(by_table.items()):
                t = self._tables[tname]
                arrs = {}
                for n in names:
                    v = feed[n]
                    if getattr(v, "lod", None):
                        raise ValueError(
                            f"emb_cache: LoDTensor ids ('{n}') are not "
                            f"supported for cached table '{tname}'")
                    # host-side id ndarrays only (never device buffers):
                    # no device sync can hide here, and the remap must be
                    # atomic with the slab state the lock protects
                    arrs[n] = np.asarray(  # thread-lint: ok blocking-under-lock
                        v.array() if hasattr(v, "array") else v)
                uniq, counts = np.unique(
                    np.concatenate([a.ravel() for a in arrs.values()]),
                    return_counts=True)
                uniq = uniq.astype(np.int64)
                premissed = self._consume_prefetch(t, uniq)
                self._ensure(t, uniq, count=True,
                             counts=counts.astype(np.int64),
                             premissed=premissed)
                if not self.read_only:
                    # the dispatched step scatter-applies into exactly
                    # these slots — they diverge from the host slab
                    t.dirty[t.id2slot[uniq]] = True
                for n, a in arrs.items():
                    out[n] = t.id2slot[a].astype(a.dtype)
        return out

    def _consume_prefetch(self, t: _CachedTable, uniq: np.ndarray
                          ) -> Optional[np.ndarray]:
        """If the last prefetch covered this exact request, return the
        ids it staged as misses (for occurrence-weighted counting in
        prepare_feed — a prefetched row is still miss traffic, just
        latency-hidden); None when no usable prefetch is pending."""
        pending, t.prefetch_pending = t.prefetch_pending, None
        if pending is None:
            return None
        puniq, pmissed = pending
        if uniq.size and bool(np.isin(uniq, puniq,
                                      assume_unique=True).all()):
            return pmissed
        return None

    def prefetch(self, uniq_map: Dict[str, np.ndarray]) -> _PrefetchHandle:
        """Stage the next window's rows in a background thread while the
        current window computes. `uniq_map` maps feed id names (or table
        names) to unique-id arrays — the shape next_window(...,
        sparse_slots=[...]) returns. Call handle.wait() before the next
        prepare_feed: the maps are shared state."""
        staged: Dict[str, np.ndarray] = {}
        for key, ids in (uniq_map or {}).items():
            tname = self._ids_to_table.get(key, key)
            if tname not in self._tables:
                continue
            ids = np.asarray(ids).ravel().astype(np.int64)
            prev = staged.get(tname)
            staged[tname] = (ids if prev is None
                             else np.concatenate([prev, ids]))

        def work():
            from .. import telemetry
            with self._lock:
                for tname, ids in sorted(staged.items()):
                    t = self._tables[tname]
                    uniq = np.unique(ids)
                    missed = uniq[t.id2slot[uniq] < 0]
                    self._ensure(t, uniq, count=False)
                    t.prefetch_pending = (uniq, missed)
            telemetry.counter(
                "emb_cache_prefetch_total",
                "background hot-row prefetches issued").inc()

        return _PrefetchHandle(self, work)

    def flush(self) -> int:
        """Write every dirty slot back to the host slab (checkpoint
        barrier; io.save_vars calls this before substituting the host
        slab for the device slab). Returns bytes flushed."""
        total = 0
        with self._lock:
            for t in self._tables.values():
                d = np.flatnonzero(t.dirty & (t.slot2id >= 0))
                if not d.size:
                    t.dirty[:] = False
                    continue
                ids = t.slot2id[d]
                for name in t.state_names:
                    # the device->host sync IS the flush barrier: rows
                    # must land in t.host before the lock releases, or a
                    # concurrent prepare_feed could re-stage stale rows
                    vals = np.asarray(  # thread-lint: ok blocking-under-lock
                        self._slab(name)[d])
                    t.host[name][ids] = vals
                    total += vals.nbytes
                t.dirty[:] = False
        if total:
            self._record_flush(total)
        return total

    def host_value(self, state_name: str) -> Optional[np.ndarray]:
        """The authoritative host slab for a cached state var (None when
        the var is not cached). Call flush() first for current values."""
        with self._lock:
            for t in self._tables.values():
                if state_name in t.state_names:
                    return t.host[state_name]
        return None

    def load_host(self, state_name: str, arr: np.ndarray) -> bool:
        """Checkpoint-restore path: replace the host slab and invalidate
        the owning table's residency (every slot re-stages on first
        touch). Returns False when the var is not cached."""
        with self._lock:
            for t in self._tables.values():
                if state_name not in t.state_names:
                    continue
                arr = np.ascontiguousarray(arr)
                if arr.shape != t.host[state_name].shape:
                    raise ValueError(
                        f"emb_cache: restore of '{state_name}' has shape "
                        f"{arr.shape}, expected "
                        f"{t.host[state_name].shape} (the checkpoint "
                        f"holds the FULL host table, not the cache slab)")
                t.host[state_name] = arr
                t.id2slot[:] = -1
                t.slot2id[:] = -1
                t.freq[:] = 0
                t.last_used[:] = 0
                t.dirty[:] = False
                t.prefetch_pending = None
                return True
        return False

    # --- telemetry ----------------------------------------------------------
    def _record_rate(self, t: _CachedTable, n_hit: int, n_miss: int):
        from .. import telemetry
        if n_hit:
            telemetry.counter(
                "emb_cache_hits_total", "hot-row cache id hits",
                labels=("table",)).labels(table=t.name).inc(n_hit)
        if n_miss:
            telemetry.counter(
                "emb_cache_misses_total",
                "hot-row cache id misses (rows staged from host DRAM)",
                labels=("table",)).labels(table=t.name).inc(n_miss)
        total = t.hits + t.misses
        if total:
            telemetry.gauge(
                "emb_cache_hit_rate",
                "cumulative hot-row cache hit rate (hits / ids resolved)",
                labels=("table",)).labels(table=t.name).set(
                    t.hits / total)

    def _note_overlap(self, dur: float, overlapped: float):
        from .. import telemetry
        with self._lock:
            self._prefetch_seconds += dur
            self._overlap_seconds += overlapped
            frac = (self._overlap_seconds / self._prefetch_seconds
                    if self._prefetch_seconds > 0 else 0.0)
        telemetry.gauge(
            "emb_cache_prefetch_overlap_fraction",
            "fraction of prefetch wall time hidden behind the in-flight "
            "window's compute").set(frac)

    def _record_flush(self, nbytes: int):
        from .. import telemetry
        with self._lock:
            self._flush_bytes += int(nbytes)
        telemetry.counter(
            "emb_cache_flush_bytes_total",
            "dirty hot-row bytes written back to the host-DRAM "
            "authoritative store").inc(int(nbytes))


# --- activation -------------------------------------------------------------

def _discover(program, only: Optional[Sequence[str]] = None) -> Dict[str, Dict]:
    """{table name: {dim, rows, ids, ops}} for every lookup_table W the
    cache could serve, with the op-set / padding / sharding / sparse
    validations that keep the slot remap sound."""
    blk = program.global_block()
    found: Dict[str, Dict] = {}
    produced = {n for op in blk.ops for n in op.output_arg_names}
    for op in blk.ops:
        if op.type != "lookup_table":
            continue
        wname = (op.input("W") or [None])[0]
        ids = (op.input("Ids") or [None])[0]
        if not wname or not blk.has_var(wname):
            continue
        if only is not None and wname not in only:
            continue
        if int(op.desc.attrs.get("padding_idx", -1)) >= 0:
            raise ValueError(
                f"emb_cache: table '{wname}' uses padding_idx — the "
                f"lookup compares raw ids against it, which slot "
                f"remapping breaks; drop padding_idx or the cache")
        if not op.desc.attrs.get("is_sparse", False):
            raise ValueError(
                f"emb_cache: table '{wname}' has is_sparse=False — a "
                f"dense gradient would update every cache slot "
                f"(including stale tenants); build the embedding with "
                f"is_sparse=True so only touched slots scatter-apply")
        if ids in produced:
            raise ValueError(
                f"emb_cache: ids '{ids}' of table '{wname}' are computed "
                f"in-graph; the cache remaps ids at feed time, so they "
                f"must be a fed input")
        ent = found.setdefault(wname, {"ids": [], "ops": []})
        if ids not in ent["ids"]:
            ent["ids"].append(ids)
    from . import embedding as embedding_mod
    for wname, ent in found.items():
        if wname in embedding_mod.sharded_tables(program):
            raise ValueError(
                f"emb_cache: table '{wname}' is row-sharded "
                f"(_sharded_tables) — the hot-row cache replaces the "
                f"beyond-HBM role of sharding; pick one per table")
        offenders = []
        for i, op in enumerate(blk.ops):
            names = set(op.input_arg_names) | set(op.output_arg_names)
            if wname in names and op.type not in CACHE_AWARE_OPS:
                offenders.append(f"op[{i}] {op.type}")
        if offenders:
            raise ValueError(
                f"emb_cache: table '{wname}' is referenced by "
                f"{offenders}, which have no slot-remap path "
                f"(CACHE_AWARE_OPS = {sorted(CACHE_AWARE_OPS)}) — such "
                f"an op would index the cache slab with global row ids")
        shp = tuple(blk.var(wname).shape or ())
        ent["rows"] = int(shp[0])
        ent["dim"] = int(shp[1]) if len(shp) > 1 else 1
    return found


def enable(program, budget_bytes: Optional[int] = None,
           tables: Optional[Dict[str, int]] = None, scope=None,
           headroom=None, batch: Optional[int] = None,
           limit_bytes: Optional[int] = None,
           window_feed_bytes: int = 0) -> Optional[EmbCache]:
    """Activate the hot-row cache on `program` (call AFTER the startup
    program ran and the optimizer was applied — the table and its
    accumulators must already exist in the scope).

    cache_rows per table comes from, in priority order: an explicit
    `tables={name: cache_rows}` entry, a layers.embedding(cache_rows=N)
    request, or `budget_bytes` split evenly over the remaining tables
    (each row costs dim * itemsize * (1 + n_accumulators) device
    bytes). Pass `headroom=` (a memory.HeadroomModel) + `batch=` to
    derive the budget from measured headroom minus the window feed
    buffer instead. A table whose cache_rows would cover the whole
    table is left uncached (it fits in HBM already).

    Swaps the scope's full [rows, dim] arrays for [cache_rows, dim]
    slabs, keeps the full arrays as host-DRAM authoritative slabs, and
    installs the EmbCache on `program._emb_cache` — the executor remaps
    feeds automatically from then on. Returns the cache (None when the
    PADDLE_TPU_EMB_CACHE kill-switch is off or nothing needs caching).
    """
    if not cache_enabled():
        return None
    from .. import executor as executor_mod
    from . import embedding as embedding_mod
    scope = scope if scope is not None else executor_mod.global_scope()
    if getattr(program, "_emb_cache", None) is not None:
        return program._emb_cache
    requested = requested_rows(program)
    if tables is not None:
        only = list(tables)
    elif requested:
        only = list(requested)
    else:
        only = None
    if budget_bytes is None and headroom is not None:
        if batch is None:
            raise ValueError("enable(headroom=...) needs batch=")
        budget_bytes = budget_from_headroom(
            headroom, batch, limit_bytes, window_feed_bytes)
    found = _discover(program, only)
    if not found:
        return None

    blk = program.global_block()
    specs: List[_CachedTable] = []
    sized_by_budget = [
        w for w in found
        if not (tables and w in tables) and w not in requested]
    for wname, ent in sorted(found.items()):
        state = [wname] + embedding_mod.table_accumulators(program, wname)
        if tables and wname in tables:
            cache_rows = int(tables[wname])
        elif wname in requested:
            cache_rows = int(requested[wname])
        elif budget_bytes is not None:
            v = scope.find_var(wname)
            itemsize = (np.asarray(v).dtype.itemsize
                        if v is not None else 4)
            cache_rows = rows_for_budget(
                max(0, int(budget_bytes)) // max(1, len(sized_by_budget)),
                ent["dim"], itemsize, len(state))
        else:
            raise ValueError(
                f"emb_cache.enable: no cache_rows for table '{wname}' — "
                f"pass budget_bytes=/tables= or build the layer with "
                f"cache_rows=")
        if cache_rows >= ent["rows"]:
            continue   # fits in HBM as-is; nothing to cache
        if cache_rows < 1:
            raise ValueError(
                f"emb_cache.enable: budget leaves {cache_rows} cache "
                f"rows for table '{wname}' ({ent['rows']}x{ent['dim']}) "
                f"— raise budget_bytes")
        t = _CachedTable(wname, ent["rows"], ent["dim"], cache_rows,
                         state, ent["ids"])
        for name in state:
            v = scope.find_var(name)
            if v is None:
                raise RuntimeError(
                    f"emb_cache.enable: '{name}' is absent from the "
                    f"scope — run the startup program (and the "
                    f"optimizer's minimize) before enabling the cache")
            host = np.array(np.asarray(
                v.array() if hasattr(v, "array") else v))
            if host.shape[0] != ent["rows"]:
                raise ValueError(
                    f"emb_cache.enable: scope var '{name}' has "
                    f"{host.shape[0]} rows, table declares {ent['rows']}")
            t.host[name] = host
            scope.set_var(
                name, np.zeros((cache_rows,) + host.shape[1:],
                               dtype=host.dtype))
        specs.append(t)
    if not specs:
        return None

    cache = EmbCache(
        program, specs,
        get_state=scope.find_var,
        set_state=scope.set_var)
    program._emb_cache = cache
    # state avals changed shape: invalidate executor-compiled blocks
    program._version = getattr(program, "_version", 0) + 1
    from .. import telemetry
    telemetry.log_event(
        "emb_cache_enable",
        tables={t.name: {"rows": t.rows, "cache_rows": t.cache_rows,
                         "state": len(t.state_names)} for t in specs},
        budget_bytes=budget_bytes)
    return cache


def enable_serving(engine, budget_bytes: Optional[int] = None,
                   tables: Optional[Dict[str, int]] = None
                   ) -> Optional[EmbCache]:
    """Read-only variant for serving.ServingEngine: the engine's
    device-resident state dict holds the cache slab, per-request ids
    remap under the engine lock, misses stage from the host slab, and
    eviction never flushes (host DRAM stays authoritative — inference
    never writes rows). Called by ServingEngine when constructed with
    emb_cache_budget_bytes= / emb_cache_tables=."""
    if not cache_enabled():
        return None
    program = engine.program
    from . import embedding as embedding_mod   # noqa: F401 (parity import)
    found = _discover(program, list(tables) if tables else None)
    found = {w: e for w, e in found.items()
             if set(e["ids"]) <= set(engine.feed_names)}
    if not found:
        return None
    specs: List[_CachedTable] = []
    for wname, ent in sorted(found.items()):
        host = np.array(np.asarray(engine._state[wname]))
        if host.shape[0] != ent["rows"]:
            raise ValueError(
                f"emb_cache.enable_serving: resident '{wname}' has "
                f"{host.shape[0]} rows but the program declares "
                f"{ent['rows']} — the saved model appears to hold a "
                f"cache slab instead of the full table (was it exported "
                f"without flushing the training-side cache?)")
        itemsize = host.dtype.itemsize
        if tables and wname in tables:
            cache_rows = int(tables[wname])
        elif budget_bytes is not None:
            cache_rows = rows_for_budget(
                max(0, int(budget_bytes)) // max(1, len(found)),
                ent["dim"], itemsize, 1)
        else:
            raise ValueError("enable_serving needs budget_bytes= or "
                             "tables=")
        if cache_rows >= ent["rows"]:
            continue
        if cache_rows < 1:
            raise ValueError(
                f"emb_cache.enable_serving: budget leaves {cache_rows} "
                f"cache rows for '{wname}' — raise the budget")
        t = _CachedTable(wname, ent["rows"], ent["dim"], cache_rows,
                         [wname], ent["ids"])
        t.host[wname] = host
        engine._state[wname] = np.zeros(
            (cache_rows,) + host.shape[1:], dtype=host.dtype)
        specs.append(t)
    if not specs:
        return None
    cache = EmbCache(
        program, specs,
        get_state=lambda n: engine._state.get(n),
        set_state=lambda n, v: engine._state.__setitem__(n, v),
        read_only=True)
    from .. import telemetry
    telemetry.log_event(
        "emb_cache_enable_serving",
        tables={t.name: {"rows": t.rows, "cache_rows": t.cache_rows}
                for t in specs})
    return cache
