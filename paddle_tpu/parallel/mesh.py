"""Device-mesh management: the TPU-native replacement for the reference's
multi-device machinery (get_places / parallel_do / NCCL Communicator /
parameter-server endpoints — SURVEY.md §2.5).

A program tagged with a mesh runs SPMD: the executor shards feeds over the
'dp' axis and replicates parameters; XLA GSPMD inserts the grad AllReduce
over ICI (the jax.lax.psum the north star asks for comes out of the
partitioner rather than hand-written per-op)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_current_mesh: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]):
    global _current_mesh
    _current_mesh = mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh


def data_parallel_mesh(n_devices: Optional[int] = None,
                       devices: Optional[Sequence] = None) -> Mesh:
    """1-D 'dp' mesh over the first n local devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("dp",))


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices: Optional[Sequence] = None) -> Mesh:
    """N-D mesh, e.g. make_mesh((4, 2), ('dp', 'mp'))."""
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(shape))
    return Mesh(np.array(devices[:n]).reshape(shape), axis_names=tuple(axis_names))


def batch_sharding(mesh: Mesh, ndim: int, axis: str = "dp") -> NamedSharding:
    spec = [None] * ndim
    if ndim > 0:
        spec[0] = axis
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
