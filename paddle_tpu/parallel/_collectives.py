"""Shared helpers for shard_map-based collectives."""

from __future__ import annotations

import jax
from jax import lax


def mark_varying(x, axis_name: str):
    """Mark a replicated value as varying over `axis_name` for shard_map's
    varying-manifest-axis typechecker (scan carries initialized from
    replicated constants need this). Tries the current API first and
    degrades gracefully on jax versions without one."""
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        try:
            return pcast(x, (axis_name,), to="varying")
        except TypeError:
            pass
    pvary = getattr(lax, "pvary", None)
    if pvary is not None:
        return pvary(x, (axis_name,))
    return x


def tree_mark_varying(tree, axis_name: str):
    return jax.tree_util.tree_map(lambda a: mark_varying(a, axis_name), tree)
