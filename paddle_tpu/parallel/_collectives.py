"""Shared helpers for shard_map-based collectives and cross-host reduces."""

from __future__ import annotations

import numpy as np

import jax
from jax import lax


def coll_scope(site: str):
    """Named scope tagging a framework collective call site. The scope
    lands in HLO metadata op_name as 'pd.coll.<site>', which
    xplane.hlo_collectives joins back to device-time events so fleet.py
    can attribute collective cost to the emitting layer (ring-attention
    rotate, pipeline send, dp grad psum) instead of a bare HLO name."""
    return jax.named_scope(f"pd.coll.{site}")


def mark_varying(x, axis_name: str):
    """Mark a replicated value as varying over `axis_name` for shard_map's
    varying-manifest-axis typechecker (scan carries initialized from
    replicated constants need this). Tries the current API first and
    degrades gracefully on jax versions without one."""
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        try:
            return pcast(x, (axis_name,), to="varying")
        except TypeError:
            pass
    pvary = getattr(lax, "pvary", None)
    if pvary is not None:
        return pvary(x, (axis_name,))
    return x


def tree_mark_varying(tree, axis_name: str):
    return jax.tree_util.tree_map(lambda a: mark_varying(a, axis_name), tree)


# ---------------------------------------------------------------------------
# Cross-host (multi-controller) reduces — the DCN-level collectives backing
# telemetry.snapshot(reduce=True) and any other host-scalar aggregation.
# Single-process runs short-circuit without touching jax.distributed.
# ---------------------------------------------------------------------------

def host_allreduce_sum(values) -> np.ndarray:
    """Elementwise sum of a same-shaped float array across every process
    (allgather + local sum — semantically an allreduce; the gather rides
    the same DCN collective). Callers must pass identical shapes on every
    host."""
    local = np.asarray(values, dtype=np.float64)
    if jax.process_count() <= 1:
        return local
    from jax.experimental import multihost_utils
    gathered = np.asarray(multihost_utils.process_allgather(local))
    return gathered.reshape((jax.process_count(),) + local.shape).sum(axis=0)


_kv_gen = [0]


def _coordination_client():
    """The jax.distributed coordination-service client (None when the
    runtime isn't multi-process or the internal layout moved)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 - internal API, degrade to collective
        return None


def process_allgather_bytes(payload: bytes) -> list:
    """Gather one variable-length bytes payload per process, returned in
    process order — the transport for per-host metadata (serialized
    telemetry snapshots, JSON).

    Preferred path: the jax.distributed coordination service's KV store
    (control plane, DCN) — telemetry is low-rate and must not depend on
    the accelerator backend supporting multiprocess computations (the CPU
    backend does not). Fallback: a size-equalized uint8 device allgather."""
    if jax.process_count() <= 1:
        return [payload]
    client = _coordination_client()
    if client is not None:
        import base64
        gen, _kv_gen[0] = _kv_gen[0], _kv_gen[0] + 1
        base = f"paddle_tpu/allgather_bytes/{gen}"
        client.key_value_set(f"{base}/{jax.process_index()}",
                             base64.b64encode(payload).decode("ascii"))
        return [base64.b64decode(client.blocking_key_value_get(
                    f"{base}/{i}", 60_000))
                for i in range(jax.process_count())]
    from jax.experimental import multihost_utils
    data = np.frombuffer(payload, dtype=np.uint8)
    with coll_scope("host_allgather"):
        sizes = np.asarray(multihost_utils.process_allgather(
            np.array([data.size], dtype=np.int64))).reshape(-1)
        padded = np.zeros(int(sizes.max()), dtype=np.uint8)
        padded[: data.size] = data
        rows = np.asarray(multihost_utils.process_allgather(padded))
    rows = rows.reshape(jax.process_count(), -1)
    return [rows[i, : int(sizes[i])].tobytes()
            for i in range(jax.process_count())]
