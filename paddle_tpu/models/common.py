"""Shared model-building glue for the zoo."""

from __future__ import annotations

from .. import layers


def build_image_classifier(model_fn, images, label, class_dim=1000, **kwargs):
    """Attach softmax-cross-entropy classification head + accuracy to a
    backbone (the pattern every reference benchmark script repeats,
    e.g. benchmark/paddle/image/resnet.py)."""
    logits = model_fn(images, class_dim=class_dim, **kwargs)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    predict = layers.softmax(logits)
    acc = layers.accuracy(input=predict, label=label)
    return avg_cost, predict, acc
