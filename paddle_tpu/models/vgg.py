"""VGG-16/19 (reference: benchmark/paddle/image/vgg.py,
tests/book/test_image_classification.py vgg16_bn_drop,
benchmark/cluster/vgg16/vgg16_fluid.py — the distributed-scaling baseline
model)."""

from __future__ import annotations

from .. import layers, nets


def _conv_block(input, num_filter, groups, dropouts, is_test=False):
    # per reference vgg16_bn_drop: dropout after every conv in the group
    # except the last (rate 0 there)
    rates = [dropouts] * (groups - 1) + [0.0]
    return nets.img_conv_group(
        input=input, conv_num_filter=[num_filter] * groups,
        pool_size=2, pool_stride=2, conv_filter_size=3, conv_act="relu",
        conv_with_batchnorm=True, conv_batchnorm_drop_rate=rates,
        pool_type="max", is_test=is_test)


def _vgg(input, class_dim, depth_groups, fc_size=4096, with_dropout=True,
         is_test=False):
    c1 = _conv_block(input, 64, depth_groups[0], 0.3, is_test=is_test)
    c2 = _conv_block(c1, 128, depth_groups[1], 0.4, is_test=is_test)
    c3 = _conv_block(c2, 256, depth_groups[2], 0.4, is_test=is_test)
    c4 = _conv_block(c3, 512, depth_groups[3], 0.4, is_test=is_test)
    c5 = _conv_block(c4, 512, depth_groups[4], 0.4, is_test=is_test)

    drop = layers.dropout(x=c5, dropout_prob=0.5, is_test=is_test) \
        if with_dropout else c5
    fc1 = layers.fc(input=drop, size=fc_size, act=None)
    bn = layers.batch_norm(input=fc1, act="relu", is_test=is_test)
    drop2 = layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test) \
        if with_dropout else bn
    fc2 = layers.fc(input=drop2, size=fc_size, act=None)
    out = layers.fc(input=fc2, size=class_dim, act=None)
    return out


def vgg16(input, class_dim=1000, is_test=False):
    return _vgg(input, class_dim, [2, 2, 3, 3, 3], is_test=is_test)


def vgg19(input, class_dim=1000, is_test=False):
    return _vgg(input, class_dim, [2, 2, 4, 4, 4], is_test=is_test)
