"""AlexNet (reference: benchmark/paddle/image/alexnet.py)."""

from __future__ import annotations

from .. import layers


def alexnet(input, class_dim=1000, is_test=False):
    conv1 = layers.conv2d(input=input, num_filters=64, filter_size=11,
                          stride=4, padding=2, act="relu")
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2)
    norm1 = layers.lrn(input=pool1, n=5, alpha=1e-4, beta=0.75)

    conv2 = layers.conv2d(input=norm1, num_filters=192, filter_size=5,
                          padding=2, act="relu")
    pool2 = layers.pool2d(input=conv2, pool_size=3, pool_stride=2)
    norm2 = layers.lrn(input=pool2, n=5, alpha=1e-4, beta=0.75)

    conv3 = layers.conv2d(input=norm2, num_filters=384, filter_size=3,
                          padding=1, act="relu")
    conv4 = layers.conv2d(input=conv3, num_filters=256, filter_size=3,
                          padding=1, act="relu")
    conv5 = layers.conv2d(input=conv4, num_filters=256, filter_size=3,
                          padding=1, act="relu")
    pool3 = layers.pool2d(input=conv5, pool_size=3, pool_stride=2)

    drop1 = layers.dropout(x=pool3, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(input=drop1, size=4096, act="relu")
    drop2 = layers.dropout(x=fc1, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(input=drop2, size=4096, act="relu")
    out = layers.fc(input=fc2, size=class_dim, act=None)
    return out
