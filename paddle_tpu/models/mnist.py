"""MNIST models (reference: tests/book/test_recognize_digits.py mlp + conv,
benchmark/paddle/image/smallnet_mnist_cifar.py)."""

from __future__ import annotations

from .. import layers, nets


def mnist_mlp(input, class_dim=10, is_test=False):
    h1 = layers.fc(input=input, size=200, act="tanh")
    h2 = layers.fc(input=h1, size=200, act="tanh")
    return layers.fc(input=h2, size=class_dim, act=None)


def mnist_conv(input, class_dim=10, is_test=False):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=input, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    return layers.fc(input=conv_pool_2, size=class_dim, act=None)
