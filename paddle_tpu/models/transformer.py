"""Decoder-only transformer language model on the layers DSL.

The 2018 reference has no attention op at all (its sequence story is LoD
RNNs, SURVEY.md §2.5 last row) — this is the repo's north-star long-context
config: pre-LN GPT-style blocks whose attention lowers to the Pallas flash
kernels (ops/pallas_attention.py) with use_flash=True, and to ring
attention over an 'sp' mesh axis with sequence_parallel=True
(parallel/ring_attention.py). Benchmark: BENCH_MODE=transformer.
"""

from __future__ import annotations

from .. import layers
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def transformer_lm(tokens, labels, vocab_size, d_model=512, n_head=8,
                   n_layer=4, ffn_mult=4, dropout_prob=0.0, is_test=False,
                   use_flash="auto", sequence_parallel=False,
                   return_logits=False):
    """tokens/labels [B, T] int -> mean next-token cross-entropy loss.

    Pre-LN residual blocks: x += Wo·attn(LN(x)); x += W2·gelu(W1·LN(x)).
    Causal attention over [B, T, H, D] via fused_attention, so one flag
    flips the whole model between the XLA einsum path, the Pallas flash
    kernels, and ring sequence parallelism.

    With return_logits=True returns (loss, logits) where logits is the
    pre-softmax [B, T, V] head output — the inference fetch the serving
    subsystem prunes to (token-level latency scenario); the training tail
    hangs off loss only, so pruning to logits drops it entirely."""
    seqlen = int(tokens.shape[-1])
    d_head = d_model // n_head
    assert d_head * n_head == d_model

    x = layers.embedding(tokens, size=[vocab_size, d_model],
                         param_attr=ParamAttr(
                             initializer=NormalInitializer(scale=0.02)))
    pos = layers.create_parameter(
        shape=[seqlen, d_model], dtype="float32", name="pos_emb",
        default_initializer=NormalInitializer(scale=0.01))
    x = layers.elementwise_add(x, pos, axis=1)          # [B, T, D]
    if dropout_prob and not is_test:
        x = layers.dropout(x, dropout_prob, is_test=is_test)

    def _proj(h, size, act=None):
        return layers.fc(input=h, size=size, num_flatten_dims=2, act=act,
                         param_attr=ParamAttr(
                             initializer=NormalInitializer(scale=0.02)))

    for _ in range(n_layer):
        h = layers.layer_norm(x, begin_norm_axis=2)
        q = layers.reshape(_proj(h, d_model), [-1, seqlen, n_head, d_head])
        k = layers.reshape(_proj(h, d_model), [-1, seqlen, n_head, d_head])
        v = layers.reshape(_proj(h, d_model), [-1, seqlen, n_head, d_head])
        attn = layers.fused_attention(q, k, v, causal=True,
                                      use_flash=use_flash,
                                      sequence_parallel=sequence_parallel)
        attn = layers.reshape(attn, [-1, seqlen, d_model])
        x = layers.elementwise_add(x, _proj(attn, d_model))

        h = layers.layer_norm(x, begin_norm_axis=2)
        ff = _proj(h, ffn_mult * d_model, act="gelu")
        x = layers.elementwise_add(x, _proj(ff, d_model))

    x = layers.layer_norm(x, begin_norm_axis=2)
    logits = _proj(x, vocab_size)                        # [B, T, V]
    flat = layers.reshape(logits, [-1, vocab_size])
    lab = layers.reshape(labels, [-1, 1])
    loss = layers.softmax_with_cross_entropy(logits=flat, label=lab)
    mean_loss = layers.mean(loss)
    if return_logits:
        return mean_loss, logits
    return mean_loss
