"""Model zoo: the reference's benchmark + book models, built on the layers DSL.

Reference model scripts: benchmark/paddle/image/{alexnet,googlenet,resnet,vgg,
smallnet_mnist_cifar}.py and python/paddle/fluid/tests/book/*. Each builder
takes the input Variable(s) and returns logits/prediction Variables; training
glue (loss, optimizer) stays in user code or in `build_classifier`.
"""

from .alexnet import alexnet
from .googlenet import googlenet
from .mnist import mnist_conv, mnist_mlp
from .resnet import resnet_cifar10, resnet_imagenet, resnet50
from .smallnet import smallnet_mnist_cifar
from .transformer import transformer_lm
from .vgg import vgg16, vgg19
from .common import build_image_classifier

__all__ = [
    "alexnet", "googlenet", "mnist_conv", "mnist_mlp",
    "resnet_cifar10", "resnet_imagenet", "resnet50",
    "smallnet_mnist_cifar", "transformer_lm",
    "vgg16", "vgg19", "build_image_classifier",
]
