"""GoogLeNet / Inception-v1 (reference: benchmark/paddle/image/googlenet.py).

Only the main classifier head is returned (the reference benchmark also drops
the aux heads for timing)."""

from __future__ import annotations

from .. import layers


def _inception(input, c1, c3r, c3, c5r, c5, proj):
    conv1 = layers.conv2d(input=input, num_filters=c1, filter_size=1,
                          act="relu")
    conv3r = layers.conv2d(input=input, num_filters=c3r, filter_size=1,
                           act="relu")
    conv3 = layers.conv2d(input=conv3r, num_filters=c3, filter_size=3,
                          padding=1, act="relu")
    conv5r = layers.conv2d(input=input, num_filters=c5r, filter_size=1,
                           act="relu")
    conv5 = layers.conv2d(input=conv5r, num_filters=c5, filter_size=5,
                          padding=2, act="relu")
    pool = layers.pool2d(input=input, pool_size=3, pool_stride=1,
                         pool_padding=1, pool_type="max")
    convprj = layers.conv2d(input=pool, num_filters=proj, filter_size=1,
                            act="relu")
    return layers.concat([conv1, conv3, conv5, convprj], axis=1)


def googlenet(input, class_dim=1000, is_test=False):
    conv1 = layers.conv2d(input=input, num_filters=64, filter_size=7,
                          stride=2, padding=3, act="relu")
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                          pool_type="max")
    conv2r = layers.conv2d(input=pool1, num_filters=64, filter_size=1,
                           act="relu")
    conv2 = layers.conv2d(input=conv2r, num_filters=192, filter_size=3,
                          padding=1, act="relu")
    pool2 = layers.pool2d(input=conv2, pool_size=3, pool_stride=2,
                          pool_type="max")

    i3a = _inception(pool2, 64, 96, 128, 16, 32, 32)
    i3b = _inception(i3a, 128, 128, 192, 32, 96, 64)
    pool3 = layers.pool2d(input=i3b, pool_size=3, pool_stride=2,
                          pool_type="max")

    i4a = _inception(pool3, 192, 96, 208, 16, 48, 64)
    i4b = _inception(i4a, 160, 112, 224, 24, 64, 64)
    i4c = _inception(i4b, 128, 128, 256, 24, 64, 64)
    i4d = _inception(i4c, 112, 144, 288, 32, 64, 64)
    i4e = _inception(i4d, 256, 160, 320, 32, 128, 128)
    pool4 = layers.pool2d(input=i4e, pool_size=3, pool_stride=2,
                          pool_type="max")

    i5a = _inception(pool4, 256, 160, 320, 32, 128, 128)
    i5b = _inception(i5a, 384, 192, 384, 48, 128, 128)
    pool5 = layers.pool2d(input=i5b, pool_size=7, pool_type="avg",
                          global_pooling=True)
    drop = layers.dropout(x=pool5, dropout_prob=0.4, is_test=is_test)
    out = layers.fc(input=drop, size=class_dim, act=None)
    return out
