"""ResNet for ImageNet (ResNet-50/101/152) and CIFAR-10.

Reference: benchmark/paddle/image/resnet.py (v2 config DSL) and
tests/book/test_image_classification.py resnet_cifar10. Rebuilt on the fluid
layers DSL: conv+bn blocks map to single XLA fusions; all matmuls/convs land
on the MXU. The flagship bench model (bench.py) is resnet50.
"""

from __future__ import annotations

from .. import layers


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_in, ch_out, stride, is_test=False):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test)
    return input


def basicblock(input, ch_in, ch_out, stride, is_test=False):
    short = shortcut(input, ch_in, ch_out, stride, is_test=is_test)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_in, ch_out, stride, is_test=False):
    short = shortcut(input, ch_in, ch_out * 4, stride, is_test=is_test)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_fn, input, ch_in, ch_out, count, stride, is_test=False):
    out = block_fn(input, ch_in, ch_out, stride, is_test=is_test)
    ch_in = out.shape[1]
    for _ in range(count - 1):
        out = block_fn(out, ch_in, ch_out, 1, is_test=is_test)
    return out


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    """ResNet for 224x224 ImageNet (reference benchmark/paddle/image/resnet.py)."""
    cfg = {
        18: ([2, 2, 2, 2], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_fn = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test)
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                          pool_padding=1, pool_type="max")
    res1 = layer_warp(block_fn, pool1, 64, 64, stages[0], 1, is_test=is_test)
    res2 = layer_warp(block_fn, res1, res1.shape[1], 128, stages[1], 2,
                      is_test=is_test)
    res3 = layer_warp(block_fn, res2, res2.shape[1], 256, stages[2], 2,
                      is_test=is_test)
    res4 = layer_warp(block_fn, res3, res3.shape[1], 512, stages[3], 2,
                      is_test=is_test)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                          global_pooling=True)
    out = layers.fc(input=pool2, size=class_dim, act=None)
    return out


def resnet50(input, class_dim=1000, is_test=False):
    """The flagship/bench model (BASELINE.json north star)."""
    return resnet_imagenet(input, class_dim=class_dim, depth=50,
                           is_test=is_test)


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    """ResNet for 32x32 CIFAR-10 (reference tests/book/
    test_image_classification.py resnet_cifar10)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test)
    res1 = layer_warp(basicblock, conv1, 16, 16, n, 1, is_test=is_test)
    res2 = layer_warp(basicblock, res1, 16, 32, n, 2, is_test=is_test)
    res3 = layer_warp(basicblock, res2, 32, 64, n, 2, is_test=is_test)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         global_pooling=True)
    out = layers.fc(input=pool, size=class_dim, act=None)
    return out
