"""SmallNet — the CIFAR-quick benchmark CNN (reference
benchmark/paddle/image/smallnet_mnist_cifar.py: three 5x5/3x3 convs with
3x3/stride-2 pools, fc64, softmax head; the BASELINE.md §1 "SmallNet"
rows). 32x32 color input."""

from __future__ import annotations

from .. import layers


def smallnet_mnist_cifar(input, class_dim=10, is_test=False):
    net = layers.conv2d(input=input, num_filters=32, filter_size=5,
                        stride=1, padding=2, act="relu")
    net = layers.pool2d(input=net, pool_size=3, pool_stride=2,
                        pool_padding=1, pool_type="max")
    net = layers.conv2d(input=net, num_filters=32, filter_size=5,
                        stride=1, padding=2, act="relu")
    net = layers.pool2d(input=net, pool_size=3, pool_stride=2,
                        pool_padding=1, pool_type="avg")
    net = layers.conv2d(input=net, num_filters=64, filter_size=3,
                        stride=1, padding=1, act="relu")
    net = layers.pool2d(input=net, pool_size=3, pool_stride=2,
                        pool_padding=1, pool_type="avg")
    net = layers.fc(input=net, size=64, act="relu")
    return layers.fc(input=net, size=class_dim)
