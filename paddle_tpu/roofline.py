"""Roofline performance attribution: per-op FLOPs/bytes, achieved TF/s,
and compute-/memory-bound verdicts (ISSUE 6 tentpole).

Joins three sources into one per-op table:

1. **Analytic cost model** — per-IR-op FLOPs and HBM bytes derived from
   concrete shapes/dtypes. The ProgramDesc's VarDesc shapes carry -1
   batch dims, so `program_cost` re-traces the executor's step fn under
   `jax.eval_shape` with an op observer installed
   (executor._op_observers): every op's lowering reports its actual
   input/output avals, and `_op_cost` maps (op_type, shapes) -> (flops,
   bytes). Cross-checked against XLA's own `compiled.cost_analysis()`.

2. **Measured device time** — xplane per-instruction picoseconds
   (`xplane.aggregate_dir`) joined to IR ops through each compiled
   block's HLO metadata op_name (the executor's pd.<type> named scope);
   unmapped device time pools under "(unattributed)" so fractions sum to
   the true device total. `xplane.timeline_dir` (XLine.timestamp_ns +
   XEvent.offset_ps) supplies the step-time waterfall: device compute vs
   infeed vs collectives vs host gap, plus the device duty cycle.

3. **Two-point measured roofline** — a sustained-matmul TF/s probe and
   an HBM-bandwidth probe (both cached per process; env-overridable via
   PADDLE_TPU_SUSTAINED_TFLOPS / PADDLE_TPU_HBM_GBPS for hermetic CI).
   Their ratio is the ridge intensity (flops/byte): ops whose arithmetic
   intensity sits right of the ridge are compute-bound, left of it
   memory-bound, and ops with no cost info are "unattributed".

The report also publishes continuous `mfu_nominal`, `mfu_vs_sustained`
and `device_duty_cycle` gauges through telemetry.py. Consumers:
`profiler.stop_profiler` (printed table), `python -m paddle_tpu perf`
(CLI), and `bench.py`/`tools/scaling_bench.py` (`top_ops`, `bound`,
`device_duty_cycle` JSON fields) via `capture()`.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["program_cost", "op_cost", "hlo_counts", "matmul_probe",
           "hbm_probe", "ici_probe", "ensure_probes", "ensure_ici",
           "nominal_tflops", "collect_report", "format_report", "capture",
           "waterfall", "top_ops", "UNATTRIBUTED"]

UNATTRIBUTED = "(unattributed)"


# --- analytic per-op cost model ---------------------------------------------

def _nelems(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _aval_list(slot_dict) -> List[Tuple[tuple, Any]]:
    """{slot: [tracer|None]} -> [(shape, dtype), ...] skipping Nones and
    valueless entries."""
    out = []
    for vals in (slot_dict or {}).values():
        for v in vals:
            shape = getattr(v, "shape", None)
            dtype = getattr(v, "dtype", None)
            if shape is not None and dtype is not None:
                out.append((tuple(shape), dtype))
    return out


def _slot_shape(slot_dict, slot) -> Optional[tuple]:
    for v in (slot_dict or {}).get(slot, []):
        shape = getattr(v, "shape", None)
        if shape is not None:
            return tuple(shape)
    return None


def _suffix_shape(slot_dict, suffix) -> Optional[tuple]:
    """First concrete shape whose slot is `suffix` or ends in `:suffix` —
    fused window ops prefix member slots as "<idx>:<slot>"."""
    for slot in (slot_dict or {}):
        if slot == suffix or slot.endswith(":" + suffix):
            s = _slot_shape(slot_dict, slot)
            if s is not None:
                return s
    return None


def _suffix_attr(attrs, suffix, default=None):
    for k, v in (attrs or {}).items():
        if k == suffix or k.endswith(":" + suffix):
            return v
    return default


def _bytes_of(avals) -> int:
    total = 0
    for shape, dtype in avals:
        total += _nelems(shape) * np.dtype(dtype).itemsize
    return total


# Multipliers: a backward op roughly doubles the forward work (dX and dW
# are each one forward-shaped contraction for matmul/conv families).
_GRAD_FACTOR = 2.0

# flops per element for the "roughly k ops per element" families; the
# model is deliberately coarse (roofline verdicts need the right order of
# magnitude and the matmul/conv terms dominate any real step).
_ELEMWISE_COST = {
    "softmax": 5.0, "log_softmax": 5.0, "batch_norm": 5.0,
    "layer_norm": 5.0, "group_norm": 5.0, "sigmoid": 4.0, "tanh": 4.0,
    "exp": 2.0, "gelu": 8.0, "swish": 5.0, "dropout": 2.0,
    "cross_entropy": 4.0, "softmax_with_cross_entropy": 8.0,
}

# flops per parameter element for the bucketed fused optimizer applies
# (ops/fusion.py): sgd = mul+sub; momentum adds the velocity update;
# adam adds two moment EMAs, the bias-corrected lr and the rsqrt-divide.
_FUSED_OPT_COST = {"fused_sgd": 2.0, "fused_momentum": 5.0,
                   "fused_adam": 12.0}


def _fused_cost(op_type: str, ins, outs, attrs) -> Tuple[float, float]:
    """Cost of a fused window/bucket op (ops/fusion.py). Window ops carry
    member slots prefixed "<idx>:<slot>" and member attrs prefixed
    "<idx>:<attr>"; optimizer buckets use natural multi-value slots."""
    in_avals = _aval_list(ins)
    out_avals = _aval_list(outs)
    bytes_ = float(_bytes_of(in_avals) + _bytes_of(out_avals))
    out_elems = sum(_nelems(s) for s, _ in out_avals)
    in_elems = sum(_nelems(s) for s, _ in in_avals)
    if op_type == "fused_conv_bn_act":
        filt = _suffix_shape(ins, "Filter")
        y = _suffix_shape(outs, "Y") or _suffix_shape(outs, "Output")
        if filt is not None and y is not None:
            flops = (2.0 * _nelems(y) * filt[1] * filt[-2] * filt[-1]
                     + 10.0 * _nelems(y))     # bn stats + normalize + act
        else:
            flops = float(out_elems)
    elif op_type == "fused_bn_act":
        y = _suffix_shape(outs, "Y")
        flops = 6.0 * float(_nelems(y) if y is not None
                            else max(in_elems, out_elems))
    elif op_type in _FUSED_OPT_COST:
        p_elems = sum(_nelems(getattr(v, "shape", ()))
                      for v in (ins or {}).get("Param", [])
                      if getattr(v, "shape", None) is not None)
        flops = _FUSED_OPT_COST[op_type] * float(p_elems or out_elems)
    else:
        # fused_fc_act (matmul + bias + act) or fused_chain (one-ish flop
        # per produced element; XLA DCEs the unread member outputs)
        x = _suffix_shape(ins, "X")
        out_shape = _suffix_shape(outs, "Out")
        ncol = int(_suffix_attr(attrs, "x_num_col_dims", 1) or 1)
        if op_type == "fused_fc_act" and x is not None \
                and out_shape is not None:
            flops = (2.0 * _nelems(out_shape) * _nelems(x[ncol:])
                     + 2.0 * _nelems(out_shape))
        else:
            flops = float(out_elems)
    return flops, bytes_


def op_cost(op_type: str, ins: Dict[str, list], outs: Dict[str, list],
            attrs=None) -> Tuple[float, float]:
    """(flops, hbm_bytes) for one lowered op given its concrete avals.
    Bytes are the unfused lower bound: every input read once + every
    output written once (XLA fusion only shrinks this, so intensity is a
    floor and the memory-bound verdict conservative)."""
    attrs = attrs or {}
    if op_type.startswith("fused_"):
        return _fused_cost(op_type, ins, outs, attrs)
    in_avals = _aval_list(ins)
    out_avals = _aval_list(outs)
    bytes_ = float(_bytes_of(in_avals) + _bytes_of(out_avals))
    out_elems = sum(_nelems(s) for s, _ in out_avals)
    in_elems = sum(_nelems(s) for s, _ in in_avals)

    grad = op_type.endswith("_grad")
    base = op_type[:-5] if grad else op_type
    flops: float

    if base in ("conv2d", "depthwise_conv2d", "conv2d_transpose"):
        filt = _slot_shape(ins, "Filter")
        out_shape = (_slot_shape(outs, "Output") or _slot_shape(outs, "Out"))
        if grad and out_shape is None:
            # grad op outputs are dX/dW; the conv-shaped tensor is the
            # Output@GRAD input
            out_shape = _slot_shape(ins, "Output@GRAD")
        if filt is not None and out_shape is not None:
            # filter [Cout, Cin/groups, kh, kw]: grouped and depthwise
            # convs already carry the per-group Cin in dim 1
            cin_per_group, kh, kw = filt[1], filt[-2], filt[-1]
            flops = 2.0 * _nelems(out_shape) * cin_per_group * kh * kw
        else:
            flops = float(out_elems)
    elif base in ("mul", "matmul", "matmul_v2", "fc"):
        x = _slot_shape(ins, "X") or _slot_shape(ins, "Input")
        out_shape = _slot_shape(outs, "Out")
        if grad and out_shape is None:
            out_shape = _slot_shape(ins, "Out@GRAD")
        if x is not None and out_shape is not None and len(x) >= 1:
            if base == "mul":
                ncol = int(attrs.get("x_num_col_dims", 1) or 1)
                k = _nelems(x[ncol:])
            else:
                tx = bool(attrs.get("transpose_X",
                                    attrs.get("trans_x", False)))
                k = x[-2] if (tx and len(x) >= 2) else x[-1]
            flops = 2.0 * _nelems(out_shape) * int(k)
        else:
            flops = float(out_elems)
    elif "attention" in base:
        # scores + weighted sum: 2 * (2 * B*H*T^2*D) = 4*T*q_elems
        q = (_slot_shape(ins, "Q") or _slot_shape(ins, "Query")
             or _slot_shape(ins, "X"))
        if q is not None and len(q) >= 2:
            t = q[-2] if len(q) >= 3 else q[0]
            flops = 4.0 * _nelems(q) * int(t)
        else:
            flops = float(out_elems)
    elif base.startswith("reduce_") or base in ("mean", "sum"):
        flops = float(in_elems)
    elif base.startswith("pool"):
        ksize = attrs.get("ksize") or []
        win = _nelems(ksize) if ksize else 1
        flops = float(out_elems * max(win, 1))
    elif base in ("lookup_table", "lookup_table_v2", "embedding", "gather",
                  "reshape", "reshape2", "transpose", "transpose2",
                  "concat", "split", "fill_constant", "assign", "cast",
                  "shape", "slice", "squeeze", "squeeze2", "unsqueeze",
                  "unsqueeze2", "flatten", "flatten2"):
        flops = 0.0     # pure data movement: bytes dominate
    elif base in _ELEMWISE_COST:
        flops = _ELEMWISE_COST[base] * float(max(in_elems, out_elems))
    else:
        # default: one flop per output element (elementwise family)
        flops = float(out_elems)

    if grad:
        flops *= _GRAD_FACTOR
    return flops, bytes_


def _shape_sig(ins, outs):
    """Shape tag for one op instance: the largest output's dims, plus the
    filter kernel dims for convs ("4x56x56x128|k3x3")."""
    best = None
    for vals in (outs or {}).values():
        for v in vals or []:
            shp = getattr(v, "shape", None)
            if shp is not None and (
                    best is None or np.prod(shp) > np.prod(best)):
                best = tuple(int(x) for x in shp)
    if best is None:
        return None
    sig = "x".join(str(x) for x in best)
    for v in (ins or {}).get("Filter", []) or []:
        shp = getattr(v, "shape", None)
        if shp is not None and len(shp) >= 2:
            sig += "|k" + "x".join(str(int(x)) for x in shp[-2:])
            break
    return sig


def program_cost(executor, program, feed_avals: Dict[str, Any],
                 state_avals: Dict[str, Any]) -> Dict[str, Any]:
    """Analytic per-op-type cost table for ONE step of `program`:
    {"ops": {op_type: {"flops","bytes","count"}}, "total_flops",
    "total_bytes"}. Traces the executor's step fn under jax.eval_shape —
    abstract, nothing executes, but every op observer callback sees the
    concrete shapes the ProgramDesc cannot provide (-1 batch dims)."""
    import jax
    from . import executor as executor_mod
    from . import quant

    table: Dict[str, Dict[str, float]] = {}
    qmode = getattr(program, "_quant_mode", None)

    def _peak_factor(op, ins, attrs):
        """2.0 when this instance routes through the int8/fp8 path (the
        MXU's int8 peak is 2x its bf16 peak, so the compute roofline
        doubles), else 1.0. Replays the lowering gate on the observed
        avals; convs are probed in both layout interpretations because
        the observer cannot see the trace-time layout tags — a shape
        that gates in under either is counted quantized. Best-effort by
        design: any gate error reads as the conservative 1.0."""
        if not qmode or op.type not in quant.QUANT_OPS:
            return 1.0
        try:
            if quant.gate_for_op(op.type, ins, attrs, qmode,
                                 nhwc=True) is None:
                return 2.0
            if op.type in ("conv2d", "depthwise_conv2d") and \
                    quant.gate_for_op(op.type, ins, attrs, qmode,
                                      nhwc=False) is None:
                return 2.0
        except Exception:  # noqa: BLE001
            pass
        return 1.0

    def observe(op, ins, outs):
        try:
            attrs = dict(getattr(op.desc, "attrs", {}) or {})
        except Exception:  # noqa: BLE001
            attrs = {}
        flops, bytes_ = op_cost(op.type, ins, outs, attrs)
        acc = table.setdefault(op.type,
                               {"flops": 0.0, "bytes": 0.0, "count": 0,
                                "max_flops": 0.0, "shape": None,
                                "peak_factor": None})
        factor = _peak_factor(op, ins, attrs)
        acc["peak_factor"] = factor if acc["peak_factor"] is None \
            else min(acc["peak_factor"], factor)
        acc["flops"] += flops
        acc["bytes"] += bytes_
        acc["count"] += 1
        if flops >= acc["max_flops"]:
            # the kernel_efficiency scoreboard tags each op type with its
            # heaviest instance's shape, so the table names a workload
            acc["max_flops"] = flops
            acc["shape"] = _shape_sig(ins, outs)

    persist_out = executor._persistable_outputs(program)
    fn = executor._make_step_fn(program, [], persist_out, {})
    rng_aval = jax.ShapeDtypeStruct((), np.uint32)
    executor_mod._op_observers.append(observe)
    try:
        jax.eval_shape(fn, dict(feed_avals), dict(state_avals), rng_aval)
    finally:
        executor_mod._op_observers.remove(observe)
    return {"ops": table,
            "total_flops": sum(d["flops"] for d in table.values()),
            "total_bytes": sum(d["bytes"] for d in table.values())}


# --- HLO instruction / kernel counts ----------------------------------------

# one HLO instruction per "name = <shape> opcode(...)" line; tuple shapes
# contain no nested parens so the alternation stays regular
_HLO_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.-]+\s*=\s*(?:\([^)]*\)|\S+)\s+([\w-]+)\(",
    re.M)


def hlo_counts(hlo_text: str) -> Dict[str, int]:
    """{"instructions", "fusions"} for one compiled module's HLO text —
    the per-step kernel-count proxy the fusion pass is judged by: fewer
    instructions/fusions at equal math means the trace handed XLA larger
    windows. Counts every instruction line incl. fused computations'
    bodies; "fusions" counts the top-level fusion ops (≈ device kernels
    that aren't library calls)."""
    ops = _HLO_INSTR.findall(hlo_text or "")
    return {"instructions": len(ops),
            "fusions": sum(1 for o in ops if o == "fusion")}


# --- two-point measured roofline --------------------------------------------

_PROBES: Dict[str, float] = {}


def _platform() -> str:
    import jax
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        return "cpu"


def matmul_probe(n: Optional[int] = None, iters: Optional[int] = None,
                 repeats: int = 3) -> float:
    """Sustained matmul TF/s: a jitted lax.scan chain of data-dependent
    [n,n] matmuls (nothing elidable), best of `repeats`, scalar readback
    as the fence. Same methodology as bench.py's sustained probe, sized
    down automatically on CPU so tier-1 CI stays fast."""
    import jax
    import jax.numpy as jnp

    tpu = _platform() == "tpu"
    n = n or (4096 if tpu else 256)
    iters = iters or (32 if tpu else 4)
    dtype = jnp.bfloat16 if tpu else jnp.float32

    a = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)) * 0.01,
                    dtype)

    @jax.jit
    def chain(x):
        def body(c, _):
            return jnp.matmul(c, x), None
        c, _ = jax.lax.scan(body, x, None, length=iters)
        return jnp.float32(c[0, 0])

    float(chain(a))            # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(chain(a))        # scalar readback fences the whole chain
        best = min(best, time.perf_counter() - t0)
    return (2.0 * n ** 3 * iters) / best / 1e12


def hbm_probe(mbytes: Optional[int] = None, iters: Optional[int] = None,
              repeats: int = 3) -> float:
    """Sustained HBM bandwidth in GB/s: a jitted lax.scan of
    `c = c * s + x` over a large array — each iteration reads x, reads c,
    writes c (3x the array's bytes of traffic; XLA aliases c in place)."""
    import jax
    import jax.numpy as jnp

    tpu = _platform() == "tpu"
    mb = mbytes or (256 if tpu else 16)
    iters = iters or (16 if tpu else 4)
    elems = mb * (1 << 20) // 4
    x = jnp.ones((elems,), jnp.float32)

    @jax.jit
    def sweep(x):
        def body(c, _):
            return c * jnp.float32(0.999) + x, None
        c, _ = jax.lax.scan(body, x, None, length=iters)
        return jnp.float32(c[0])

    float(sweep(x))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(sweep(x))
        best = min(best, time.perf_counter() - t0)
    return (3.0 * elems * 4 * iters) / best / 1e9


def ici_probe(mbytes: Optional[int] = None, repeats: int = 3) \
        -> Optional[float]:
    """Sustained interconnect bus bandwidth in GB/s: a jitted all-reduce
    (psum) of a large array over every local device, timed end to end and
    converted with the nccl-tests 2(n-1)/n bus-bandwidth factor. On a TPU
    slice this measures ICI; on the CPU backend with forced host devices
    it measures the memcpy fabric — either way it is the link roofline
    per-collective busbw is judged against. None with < 2 devices."""
    import jax
    import jax.numpy as jnp

    n = jax.device_count()
    if n < 2:
        return None
    tpu = _platform() == "tpu"
    mb = mbytes or (64 if tpu else 8)
    elems = mb * (1 << 20) // 4
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("probe",))
    spec = jax.sharding.PartitionSpec("probe")

    @jax.jit
    def ar(x):
        y = jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))
        # reduce ONLY the sharded axis: the [elems] result is replicated,
        # forcing an all-reduce of the full payload (a scalar-producing
        # y.sum() would let XLA all-reduce just partial scalars)
        return y.sum(0)

    x = jnp.ones((n, elems), jnp.float32)
    ar(x).block_until_ready()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        ar(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    nbytes = elems * 4    # the all-reduced buffer
    return nbytes * 2.0 * (n - 1) / n / best / 1e9


def ensure_ici(probe: bool = True) -> Optional[float]:
    """Cached ICI/DCN bus bandwidth in GB/s, PADDLE_TPU_ICI_GBPS override
    first (mirrors ensure_probes). Separate from ensure_probes so the
    existing matmul/HBM callers don't pay an all-reduce probe."""
    if "ici_gbps" not in _PROBES:
        env = os.environ.get("PADDLE_TPU_ICI_GBPS")
        if env:
            _PROBES["ici_gbps"] = float(env)
        elif probe:
            try:
                _PROBES["ici_gbps"] = ici_probe()
            except Exception:  # noqa: BLE001 - probe is advisory
                _PROBES["ici_gbps"] = None
        else:
            return None
    return _PROBES.get("ici_gbps")


def ensure_probes(probe: bool = True) -> Dict[str, Optional[float]]:
    """{"sustained_tflops","hbm_gbps","ridge"} — measured once per process
    and cached; PADDLE_TPU_SUSTAINED_TFLOPS / PADDLE_TPU_HBM_GBPS env
    overrides skip the measurement entirely (hermetic CI, or reusing the
    numbers a previous bench measured on the same host)."""
    if "sustained_tflops" not in _PROBES:
        env = os.environ.get("PADDLE_TPU_SUSTAINED_TFLOPS")
        if env:
            _PROBES["sustained_tflops"] = float(env)
        elif probe:
            try:
                _PROBES["sustained_tflops"] = matmul_probe()
            except Exception:  # noqa: BLE001 - probe is advisory
                _PROBES["sustained_tflops"] = None
    if "hbm_gbps" not in _PROBES:
        env = os.environ.get("PADDLE_TPU_HBM_GBPS")
        if env:
            _PROBES["hbm_gbps"] = float(env)
        elif probe:
            try:
                _PROBES["hbm_gbps"] = hbm_probe()
            except Exception:  # noqa: BLE001
                _PROBES["hbm_gbps"] = None
    tf = _PROBES.get("sustained_tflops")
    bw = _PROBES.get("hbm_gbps")
    ridge = (tf * 1e12) / (bw * 1e9) if tf and bw else None
    return {"sustained_tflops": tf, "hbm_gbps": bw, "ridge": ridge}


def nominal_tflops() -> Optional[float]:
    """Datasheet peak for mfu_nominal: BENCH_PEAK_TFLOPS (shared with
    bench.py, default 197 = v5e bf16) on TPU; None on CPU (no meaningful
    nominal — mfu_vs_sustained is the honest number there)."""
    env = os.environ.get("PADDLE_TPU_PEAK_TFLOPS") \
        or os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    return 197.0 if _platform() == "tpu" else None


# --- waterfall / timeline ---------------------------------------------------

_COLLECTIVE_PAT = ("all-reduce", "allreduce", "all-gather", "allgather",
                   "reduce-scatter", "reducescatter", "collective",
                   "all-to-all", "alltoall", "permute", "send", "recv")
_INFEED_PAT = ("infeed", "outfeed", "copy", "transfer", "memcpy", "h2d",
               "d2h", "host-to-device", "device-to-host", "dynamic-update")


def _bucket(event_name: str) -> str:
    low = event_name.lower()
    if any(p in low for p in _COLLECTIVE_PAT):
        return "collective"
    if any(p in low for p in _INFEED_PAT):
        return "infeed"
    return "compute"


def waterfall(trace_dir) -> Optional[Dict[str, Any]]:
    """Step-time waterfall from the xplane timeline: per device plane,
    pick the busiest XLine (the raw XLA-op line; derived step/module
    lines duplicate it), bucket its events into compute / infeed /
    collectives, and call everything between first-event-start and
    last-event-end that no event covers the host gap. Sums across device
    planes (per-core time adds up); falls back to host planes on
    CPU-backend traces."""
    from . import xplane

    records = xplane.timeline_dir(trace_dir)
    if not records:
        return None
    by_plane: Dict[str, list] = {}
    for r in records:
        by_plane.setdefault(r["plane"], []).append(r)
    planes = {p: rs for p, rs in by_plane.items()
              if p.startswith("/device:")}
    if not planes:
        # host-plane fallback (CPU backend): keep only instruction-like
        # events so the busiest-line pick lands on the XLA execution
        # thread, not the python line whose events span the whole session
        planes = {}
        for p, rs in by_plane.items():
            filtered = []
            for line in rs:
                evs = [e for e in line["events"]
                       if xplane.instr_like(e[0])]
                if evs:
                    filtered.append({**line, "events": evs})
            if filtered:
                planes[p] = filtered
        if not planes:
            return None
    out = {"compute_ps": 0, "infeed_ps": 0, "collective_ps": 0,
           "collective_exposed_ps": 0, "host_gap_ps": 0, "span_ps": 0,
           "planes": len(planes)}
    for _, lines in planes.items():
        best = None
        best_busy = -1
        for line in lines:
            busy = sum(d for _, _, d in line["events"])
            if busy > best_busy:
                best_busy, best = busy, line
        if not best or not best["events"]:
            continue
        start = min(off for _, off, _ in best["events"])
        end = max(off + d for _, off, d in best["events"])
        span = max(end - start, best_busy)
        for name, _, dur in best["events"]:
            out[_bucket(name) + "_ps"] += dur
        # exposed = collective time hidden under NO concurrent compute;
        # refines the single collectives bucket into hidden vs blocking
        out["collective_exposed_ps"] += sum(
            xplane.exposed_in_line(best["events"]).values())
        out["span_ps"] += span
        out["host_gap_ps"] += max(span - best_busy, 0)
    if not out["span_ps"]:
        return None
    out["device_duty_cycle"] = min(
        (out["compute_ps"] + out["infeed_ps"] + out["collective_ps"])
        / out["span_ps"], 1.0)
    return out


# --- the joined report ------------------------------------------------------

def collect_report(trace_dir, suppliers=(), steps: Optional[int] = None,
                   probe: bool = True) -> Optional[Dict[str, Any]]:
    """Join measured device time, the analytic cost model, and the
    two-point roofline into one report dict (see format_report for the
    printed form). `suppliers` are the profiler's (supply, cost_fn)
    pairs; `steps` is how many executor steps ran inside the trace (flops
    scale by it). Never raises on a missing piece — each absent source
    just blanks its columns."""
    from . import telemetry, xplane

    mapping: Dict[str, str] = {}
    cost: Dict[str, Dict[str, float]] = {}
    total_flops = total_bytes = 0.0
    xla_flops = 0.0
    have_cost = have_xla = False
    hlo = {"modules": 0, "instructions": 0, "fusions": 0}
    texts: List[str] = []
    notes: List[str] = []
    for pair in suppliers:
        supply, cost_fn = pair if isinstance(pair, tuple) else (pair, None)
        try:
            compiled = supply()
            text = compiled if isinstance(compiled, str) \
                else compiled.as_text()
            texts.append(text)
            mapping.update(xplane.hlo_op_names(text))
            counts = hlo_counts(text)
            hlo["modules"] += 1
            hlo["instructions"] += counts["instructions"]
            hlo["fusions"] += counts["fusions"]
            if not isinstance(compiled, str):
                try:
                    ca = compiled.cost_analysis()
                    d = ca[0] if isinstance(ca, (list, tuple)) else ca
                    xla_flops += float(d.get("flops", 0.0))
                    have_xla = True
                except Exception:  # noqa: BLE001 - backend-dependent
                    pass
        except Exception as e:  # noqa: BLE001 - table is best-effort
            notes.append(f"hlo attribution unavailable: {e}")
        if cost_fn is not None:
            try:
                t = cost_fn()
                for op_type, d in t["ops"].items():
                    acc = cost.setdefault(
                        op_type, {"flops": 0.0, "bytes": 0.0,
                                  "max_flops": 0.0, "shape": None,
                                  "peak_factor": None})
                    pf = d.get("peak_factor")
                    if pf is not None:
                        acc["peak_factor"] = pf \
                            if acc["peak_factor"] is None \
                            else min(acc["peak_factor"], pf)
                    acc["flops"] += d["flops"]
                    acc["bytes"] += d["bytes"]
                    if d.get("max_flops", 0.0) >= acc["max_flops"]:
                        acc["max_flops"] = d.get("max_flops", 0.0)
                        acc["shape"] = d.get("shape")
                total_flops += t["total_flops"]
                total_bytes += t["total_bytes"]
                have_cost = True
            except Exception as e:  # noqa: BLE001
                notes.append(
                    f"cost model unavailable: {type(e).__name__}: {e}")

    instr_ps = xplane.aggregate_dir(trace_dir)
    agg = xplane.attribute(instr_ps, mapping, other_label=UNATTRIBUTED)
    if not agg:
        return None
    total_ps = sum(agg.values())
    probes = ensure_probes(probe)
    ridge = probes["ridge"]
    sustained = probes["sustained_tflops"]
    nominal = nominal_tflops() or sustained

    rows = []
    for name, ps in sorted(agg.items(), key=lambda kv: -kv[1]):
        c = cost.get(name)
        flops = c["flops"] if c else None
        bytes_ = c["bytes"] if c else None
        tflops = intensity = None
        if flops is not None and steps and ps:
            tflops = flops * steps / (ps / 1e12) / 1e12
        if flops is not None and bytes_:
            intensity = flops / bytes_
        if name == UNATTRIBUTED or c is None:
            bound = "unattributed"
        elif intensity is not None and ridge is not None:
            bound = "compute" if intensity >= ridge else "memory"
        elif intensity is not None:
            # no bandwidth probe: fall back to the classic "MXU-shaped or
            # not" split so the verdict column never silently disappears
            bound = "compute" if intensity >= 100 else "memory"
        else:
            bound = "unattributed"
        # per-kernel scoreboard: analytic minimum device time (the larger
        # of the compute- and bandwidth-floor) vs measured — the achieved
        # fraction attributes the remaining MFU gap kernel by kernel
        min_ps = efficiency = None
        if c is not None and steps and ps:
            floors = []
            if flops and sustained:
                # int8/fp8 roofline: an op whose every instance routes
                # through the quantized path computes against the MXU's
                # doubled low-precision peak, so its analytic floor
                # halves (peak_factor from program_cost, min-combined
                # across instances — one unquantized instance pins the
                # whole op type to the bf16 roofline)
                factor = c.get("peak_factor") or 1.0
                floors.append(flops * steps / (sustained * factor * 1e12))
            if bytes_ and probes["hbm_gbps"]:
                floors.append(bytes_ * steps / (probes["hbm_gbps"] * 1e9))
            if floors:
                min_ps = max(floors) * 1e12
                if min_ps > 0:
                    efficiency = min_ps / ps
        rows.append({"op": name, "ps": ps, "frac": ps / total_ps,
                     "flops": flops, "bytes": bytes_, "tflops": tflops,
                     "intensity": intensity, "bound": bound,
                     "shape": c.get("shape") if c else None,
                     "peak_factor": (c.get("peak_factor") or 1.0)
                     if c else None,
                     "min_ps": min_ps, "efficiency": efficiency})

    wf = None
    try:
        wf = waterfall(trace_dir)
    except Exception as e:  # noqa: BLE001
        notes.append(f"waterfall unavailable: {type(e).__name__}: {e}")

    colls = None
    try:
        from . import fleet
        colls = fleet.collective_table(trace_dir, texts, steps=steps,
                                       probe=probe)
    except Exception as e:  # noqa: BLE001
        notes.append(
            f"collective attribution unavailable: {type(e).__name__}: {e}")

    report: Dict[str, Any] = {
        "trace_dir": str(trace_dir), "steps": steps,
        "device_total_ps": total_ps, "rows": rows,
        "mapped": bool(mapping), "waterfall": wf,
        "collectives": colls,
        "device_duty_cycle": (wf or {}).get("device_duty_cycle"),
        "sustained_tflops": sustained, "hbm_gbps": probes["hbm_gbps"],
        "ridge_intensity": ridge, "nominal_tflops": nominal,
        "total_flops_per_step": total_flops if have_cost else None,
        "total_bytes_per_step": total_bytes if have_cost else None,
        # fraction of the analytic flops that ride the int8/fp8 roofline
        # (peak_factor 2.0 on every instance of the op type)
        "quant_flops_fraction": (
            sum(d["flops"] for d in cost.values()
                if (d.get("peak_factor") or 1.0) > 1.0) / total_flops
            if have_cost and total_flops else None),
        "hlo_counts": hlo if hlo["modules"] else None,
        "mfu_nominal": None, "mfu_vs_sustained": None, "notes": notes,
    }
    report["kernel_efficiency"] = [
        {"op": r["op"], "shape": r["shape"],
         "ms": round(r["ps"] / 1e9, 4),
         "min_ms": round(r["min_ps"] / 1e9, 4),
         "efficiency": round(r["efficiency"], 4)}
        for r in rows if r["efficiency"] is not None]
    # fraction of device conv-family seconds served by Pallas kernels
    # (pallas lowers to custom-call instructions; lax convs to
    # convolution/fusion ones), so the bench trajectory shows coverage
    # growing as gates widen — flash-attention custom-calls map to the
    # sdpa op name and stay out of the conv family by construction
    conv_ps = pallas_ps = 0
    for instr, ps in instr_ps.items():
        op_name = mapping.get(instr)
        if op_name is None or "conv" not in op_name:
            continue
        conv_ps += ps
        if instr.split(".")[0] == "custom-call":
            pallas_ps += ps
    report["pallas_kernel_coverage"] = \
        (pallas_ps / conv_ps) if conv_ps else None
    # input-bound verdict: the waterfall blames the host input path when
    # the device idles more than it computes and infeed+host-gap dominate
    duty = report["device_duty_cycle"]
    report["input_bound"] = None
    if wf and duty is not None:
        report["input_bound"] = bool(
            duty < 0.6
            and wf["infeed_ps"] + wf["host_gap_ps"] > wf["compute_ps"])
        if report["input_bound"]:
            report["input_bound_remedy"] = (
                "step time is input-bound: raise the feeder's "
                "window_prefetch and/or use --steps-per-call auto so "
                "run_steps windows amortize host dispatch")
    if have_cost and have_xla and xla_flops > 0:
        report["cost_crosscheck"] = {
            "analytic_flops": total_flops, "xla_flops": xla_flops,
            "rel_err": abs(total_flops - xla_flops) / xla_flops}
    span_ps = (wf or {}).get("span_ps") or 0
    if have_cost and steps and span_ps:
        achieved = total_flops * steps / (span_ps / 1e12) / 1e12
        report["achieved_tflops"] = achieved
        if nominal:
            report["mfu_nominal"] = achieved / nominal
        if sustained:
            report["mfu_vs_sustained"] = achieved / sustained

    # continuous telemetry: the gauges the MFU campaign watches between
    # traced sessions, plus the per-op counters the table already fed
    for row in rows:
        telemetry.counter(
            "device_op_seconds_total",
            "device time attributed to IR ops across traced sessions",
            labels=("op",)).labels(op=row["op"]).inc(row["ps"] / 1e12)
    for row in rows:
        if row["efficiency"] is not None:
            telemetry.gauge(
                "kernel_efficiency",
                "measured device time vs analytic roofline minimum "
                "(achieved fraction), by op and heaviest shape",
                labels=("op", "shape")).labels(
                op=row["op"], shape=row["shape"] or "?").set(
                row["efficiency"])
    if report["pallas_kernel_coverage"] is not None:
        telemetry.gauge(
            "pallas_kernel_coverage",
            "fraction of device conv-family seconds served by Pallas "
            "kernels in the latest traced session").set(
            report["pallas_kernel_coverage"])
    for gname in ("mfu_nominal", "mfu_vs_sustained", "device_duty_cycle"):
        if report.get(gname) is not None:
            telemetry.gauge(
                gname, f"{gname} from the latest roofline report").set(
                    report[gname])
    # per-trace collective wait: fleet.local_snapshot and the goodput
    # ledger read these instead of re-parsing the trace. The fleet table
    # is the better source (it finds collectives on CPU traces' thread
    # lines, which the waterfall's busiest-line pick misses).
    if colls and colls.get("rows"):
        total_ms = sum(r["time_ms"] for r in colls["rows"])
        exposed_ms = sum(r["exposed_ms"] for r in colls["rows"])
    elif wf:
        total_ms = wf["collective_ps"] / 1e9
        exposed_ms = wf["collective_exposed_ps"] / 1e9
    else:
        total_ms = exposed_ms = None
    if total_ms is not None:
        telemetry.gauge(
            "collective_time_seconds",
            "total collective device time in the latest traced session"
        ).set(total_ms / 1e3)
        telemetry.gauge(
            "collective_exposed_seconds",
            "collective time not hidden under compute in the latest "
            "traced session").set(exposed_ms / 1e3)
    return report


def _fmt(v, scale=1.0, prec=2, width=9) -> str:
    if v is None:
        return f"{'-':>{width}s}"
    return f"{v / scale:{width}.{prec}f}"


def format_report(report: Dict[str, Any]) -> List[str]:
    """Render a report dict as the printed device table + waterfall +
    roofline + MFU summary lines (profiler.stop_profiler and the perf
    CLI share this). Row format keeps `[device] <op> ...` so existing
    log scrapers (and tests) still find the op in field 2."""
    lines = [f"{'Device op (jit)':40s} {'Total(ms)':>12s} {'Frac':>8s} "
             f"{'GFLOPs':>9s} {'MB':>9s} {'TF/s':>9s} {'AI':>9s}  Bound"]
    for row in report["rows"]:
        lines.append(
            f"[device] {row['op']:31s} {row['ps'] / 1e9:12.4f} "
            f"{row['frac']:8.1%} {_fmt(row['flops'], 1e9)} "
            f"{_fmt(row['bytes'], 1e6)} {_fmt(row['tflops'])} "
            f"{_fmt(row['intensity'], 1.0, 1)}  {row['bound']}")
    wf = report.get("waterfall")
    if wf:
        span = wf["span_ps"]
        coll_txt = "{:.1%}".format(wf["collective_ps"] / span)
        if wf.get("collective_exposed_ps") is not None \
                and wf["collective_ps"]:
            coll_txt += " ({:.0%} exposed)".format(
                wf["collective_exposed_ps"] / wf["collective_ps"])
        lines.append(
            "[waterfall] compute {:.1%} | infeed {:.1%} | collectives "
            "{} | host gap {:.1%}  (span {:.3f} ms)".format(
                wf["compute_ps"] / span, wf["infeed_ps"] / span,
                coll_txt, wf["host_gap_ps"] / span,
                span / 1e9))
    colls = report.get("collectives")
    if colls and colls.get("rows"):
        lines.append(
            f"{'Collective':20s} {'Call site':22s} {'MB':>9s} "
            f"{'busbw GB/s':>11s} {'% link':>7s} {'Exposed(ms)':>12s}")
        for r in colls["rows"]:
            pct = ("{:6.1%}".format(r["pct_link"])
                   if r.get("pct_link") is not None else "     -")
            lines.append(
                "[coll] {:13s} {:22s} {:9.2f} {:>11s} {} {:12.3f}".format(
                    r["kind"], r["site"], r["bytes"] / 1e6,
                    _fmt(r.get("busbw_gbps"), 1.0, 2, 11).strip().rjust(11),
                    pct, r["exposed_ms"]))
        if colls.get("ici_gbps"):
            lines.append(
                "[coll] link roofline {:.1f} GB/s ({} participants)".format(
                    colls["ici_gbps"], colls.get("participants") or "?"))
    if report.get("sustained_tflops") or report.get("hbm_gbps"):
        ridge = report.get("ridge_intensity")
        lines.append(
            "[roofline] sustained {} TF/s | hbm {} GB/s | ridge {} "
            "flops/byte".format(
                _fmt(report.get("sustained_tflops"), width=1),
                _fmt(report.get("hbm_gbps"), width=1),
                _fmt(ridge, 1.0, 1, 1)))
    ke = report.get("kernel_efficiency")
    if ke:
        lines.append(
            f"{'Kernel scoreboard':40s} {'Meas(ms)':>10s} {'Min(ms)':>10s}"
            f" {'Achieved':>9s}")
        for r in ke:
            shape = f" [{r['shape']}]" if r.get("shape") else ""
            lines.append(
                f"[kernel] {r['op']:24s}{shape:14s} {r['ms']:10.4f} "
                f"{r['min_ms']:10.4f} {r['efficiency']:9.1%}")
    cov = report.get("pallas_kernel_coverage")
    if cov is not None:
        lines.append(f"[kernel] pallas conv coverage {cov:.1%} of device "
                     f"conv-family time")
    if report.get("input_bound"):
        lines.append("[verdict] input-bound: " +
                     report.get("input_bound_remedy", ""))
    hc = report.get("hlo_counts")
    if hc:
        lines.append(
            "[hlo] {} instructions | {} fusion kernels | {} modules"
            .format(hc["instructions"], hc["fusions"], hc["modules"]))
    cc = report.get("cost_crosscheck")
    if cc:
        lines.append(
            f"[crosscheck] analytic {cc['analytic_flops'] / 1e9:.3f} "
            f"GFLOPs vs XLA {cc['xla_flops'] / 1e9:.3f} GFLOPs "
            f"(rel err {cc['rel_err']:.1%})")
    mfu_bits = []
    if report.get("mfu_nominal") is not None:
        mfu_bits.append(f"nominal {report['mfu_nominal']:.3f}")
    if report.get("mfu_vs_sustained") is not None:
        mfu_bits.append(f"vs sustained {report['mfu_vs_sustained']:.3f}")
    if report.get("device_duty_cycle") is not None:
        mfu_bits.append(f"duty cycle {report['device_duty_cycle']:.3f}")
    if mfu_bits:
        lines.append("[mfu] " + " | ".join(mfu_bits))
    for note in report.get("notes", []):
        lines.append(f"[device] ({note})")
    return lines


def top_ops(report: Dict[str, Any], k: int = 5) -> List[Dict[str, Any]]:
    """Compact per-op summary for bench JSON lines: top-k rows by device
    time, each {op, ms, frac, gflops, tflops, bound, efficiency}."""
    out = []
    for row in report["rows"][:k]:
        out.append({
            "op": row["op"], "ms": round(row["ps"] / 1e9, 4),
            "frac": round(row["frac"], 4),
            "gflops": (None if row["flops"] is None
                       else round(row["flops"] / 1e9, 3)),
            "tflops": (None if row["tflops"] is None
                       else round(row["tflops"], 3)),
            "bound": row["bound"],
            "efficiency": (None if row.get("efficiency") is None
                           else round(row["efficiency"], 4))})
    return out


def capture(run, steps: int = 3, probe: bool = True) \
        -> Optional[Dict[str, Any]]:
    """Run `run()` `steps` times inside a silent traced profiling session
    and return the roofline report (None on any failure). Nothing is
    printed — bench.py's stdout contract (one JSON line per config) stays
    intact. The temp trace dir is deleted afterwards."""
    from . import profiler as profiler_mod

    tmp = tempfile.mkdtemp(prefix="pd_roofline_")
    report = None
    try:
        profiler_mod.start_profiler(trace_dir=tmp)
        try:
            for _ in range(steps):
                run()
        finally:
            report = profiler_mod.finish_trace_report(probe=probe)
    except Exception:  # noqa: BLE001 - attribution must never kill the run
        report = None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return report
