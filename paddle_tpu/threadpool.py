"""Framework thread pool (reference: paddle/fluid/framework/
threadpool.h:33-101 — singleton GetInstance, Run -> future,
RunAndGetException, Wait; used there to drive async op execution and the
reader machinery).

Under whole-block XLA there is no per-op scheduler to feed (F16's honest
scope note), but the HOST-side consumers remain: parallel sample mapping
(reader.xmap_readers), prefetch pipelines, and user IO. This pool serves
those with the reference's API shape — including the Run vs
RunAndGetException exception contract: Run's future re-raises inside
.result() (the reference LOG(FATAL)s), RunAndGetException's future
RETURNS the exception object. Workers are DAEMON threads over an
unbounded task queue: an abandoned reader pipeline must never pin the
interpreter open at exit (the reason the pre-pool code used raw daemon
threads)."""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import Future
from typing import Callable, List, Optional

__all__ = ["ThreadPool", "get_instance"]


_SHUTDOWN = object()


class ThreadPool:
    """Bounded worker pool; `num_threads` defaults to the reference's
    choice (hardware concurrency)."""

    def __init__(self, num_threads: Optional[int] = None):
        self._n = num_threads or max(os.cpu_count() or 1, 1)
        self._tasks: queue.Queue = queue.Queue()
        self._closed = False
        self._idle = self._n
        self._lock = threading.Lock()
        self._pending: set = set()
        self._workers: List[threading.Thread] = []
        for i in range(self._n):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"paddle_tpu_pool_{i}")
            t.start()
            self._workers.append(t)

    def _worker(self):
        while True:
            item = self._tasks.get()
            if item is _SHUTDOWN:
                return
            fut, fn, args, kwargs = item
            with self._lock:
                self._idle -= 1
            try:
                if fut.set_running_or_notify_cancel():
                    try:
                        fut.set_result(fn(*args, **kwargs))
                    except BaseException as e:  # noqa: BLE001
                        fut.set_exception(e)
            finally:
                with self._lock:
                    self._idle += 1

    def threads(self) -> int:
        """(reference Threads())"""
        return self._n

    def idle_threads(self) -> int:
        """(reference IdleThreads())"""
        with self._lock:
            return max(self._idle, 0)

    def _submit(self, fn, args, kwargs) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "ThreadPool is shut down — tasks queued now would "
                    "never run and their futures would never resolve")
            self._pending.add(fut)
            # enqueue under the same lock that shutdown() takes: a task
            # that passed the closed check must land in the queue BEFORE
            # the _SHUTDOWN sentinels, or it would sit behind them forever
            # (workers exit on sentinel) and hang wait()
            self._tasks.put((fut, fn, args, kwargs))
        fut.add_done_callback(self._untrack)
        return fut

    def _untrack(self, fut):
        with self._lock:
            self._pending.discard(fut)

    def run(self, fn: Callable, *args, **kwargs) -> Future:
        """Queue fn; the future's .result() re-raises any exception
        (reference Run: failures surface on wait)."""
        return self._submit(fn, args, kwargs)

    def run_and_get_exception(self, fn: Callable, *args, **kwargs) -> Future:
        """Queue fn; the future RESOLVES TO the raised exception (or None
        on success) instead of re-raising — the reference
        RunAndGetException contract."""
        def wrapped():
            try:
                fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - contract: hand it back
                return e
            return None

        return self._submit(wrapped, (), {})

    def wait(self):
        """Block until every queued task completed (reference Wait).
        Swallows task exceptions — they belong to the futures."""
        while True:
            with self._lock:
                pending = list(self._pending)
            if not pending:
                return
            for f in pending:
                try:
                    f.exception()    # waits; does not re-raise here
                except BaseException:  # noqa: BLE001 - cancelled etc.
                    pass

    def shutdown(self):
        with self._lock:
            self._closed = True
            for _ in self._workers:
                self._tasks.put(_SHUTDOWN)

    # reference-style capitalized aliases
    Run = run
    RunAndGetException = run_and_get_exception
    Wait = wait
    Threads = threads
    IdleThreads = idle_threads


_instance: Optional[ThreadPool] = None
_instance_lock = threading.Lock()


def get_instance() -> ThreadPool:
    """Process singleton (reference ThreadPool::GetInstance)."""
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = ThreadPool()
        return _instance


GetInstance = get_instance
