"""Executor: compiles program blocks to XLA and runs them on TPU/CPU.

TPU-native replacement for the reference's interpreting executor
(reference: paddle/fluid/framework/executor.cc:96-344 Executor::Run/Prepare,
python/paddle/fluid/executor.py:182-400). The reference walks a block op by
op, dispatching each to a CUDA kernel against a mutable Scope. On TPU that
per-op dispatch model wastes the compiler: here `Executor.run` *traces the
whole block's op lowerings into a single function* — feed vars and persistable
state in, fetch vars and updated state out — and `jax.jit`s it once per
(program, feed, fetch) signature. Parameters are donated so optimizer updates
alias in-place in HBM. An eager mode (`use_jit=False` or
PADDLE_TPU_EAGER=1) interprets op-by-op like the reference, for debugging and
NaN/Inf checks (reference FLAGS_check_nan_inf, executor.cc:325-333).

Scope semantics follow the reference (scope.h:38): persistable variables live
in the global scope across runs; block-local temporaries vanish after the run.
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dynamics as dynamics_mod
from . import flags as flags_mod
from . import quant as quant_mod
from . import memory as memory_mod
from . import telemetry
from . import tracing as tracing_mod
from .framework.desc import VarType
from .framework.framework import Program, Variable, default_main_program
from .ops import registry
from .ops import sparse_ops as sparse_ops_mod

__all__ = [
    "CPUPlace", "TPUPlace", "CUDAPlace", "place_device",
    "LoDTensor", "Scope", "global_scope", "scope_guard", "Executor",
]


# ---------------------------------------------------------------------------
# Places (reference: platform/place.h:24,34,53 — CPUPlace/CUDAPlace variant).
# TPUPlace is the first-class accelerator place; CUDAPlace is accepted for
# source compatibility and maps to the same accelerator backend.
# ---------------------------------------------------------------------------

class Place:
    device_kind = "cpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __eq__(self, other):
        return (type(self) is type(other)) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    device_kind = "cpu"


class TPUPlace(Place):
    device_kind = "accelerator"


class CUDAPlace(TPUPlace):
    """Source-compat alias: scripts written for fluid.CUDAPlace(0) run on the
    TPU backend unchanged (BASELINE.json north star)."""


def place_device(place: Place):
    """Resolve a Place to a concrete jax.Device."""
    if isinstance(place, CPUPlace):
        cpus = [d for d in jax.devices("cpu")] if "cpu" in {
            d.platform for d in jax.local_devices()} else None
        if cpus is None:
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = jax.local_devices()
        return cpus[min(place.device_id, len(cpus) - 1)]
    devs = jax.local_devices()
    accel = [d for d in devs if d.platform != "cpu"] or devs
    return accel[min(place.device_id, len(accel) - 1)]


# ---------------------------------------------------------------------------
# Runtime values
# ---------------------------------------------------------------------------

class LoDTensor:
    """Runtime tensor + level-of-detail sequence offsets
    (reference: framework/lod_tensor.h:55,107). The array is padded/dense; the
    LoD records per-sequence offsets so sequence ops can mask correctly."""

    def __init__(self, array=None, lod: Optional[List[List[int]]] = None):
        self._array = array
        self.lod = lod or []

    def set(self, array, place=None):
        self._array = np.asarray(array)

    def set_lod(self, lod):
        self.lod = lod

    def array(self):
        return self._array

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    @property
    def shape(self):
        return tuple(self._array.shape)

    def recursive_sequence_lengths(self):
        return [[b - a for a, b in zip(lvl[:-1], lvl[1:])] for lvl in self.lod]


class Scope:
    """name -> runtime value, with parent chain (reference scope.h:38)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, Any] = {}
        self.kids: List[Scope] = []

    def new_scope(self) -> "Scope":
        s = Scope(self)
        self.kids.append(s)
        return s

    def var(self, name: str):
        if name not in self.vars:
            self.vars[name] = None
        return self.vars[name]

    def set_var(self, name: str, value):
        self.vars[name] = value

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return True
            s = s.parent
        return False

    def local_var_names(self):
        return list(self.vars)

    def drop_kids(self):
        self.kids = []


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


# ---------------------------------------------------------------------------
# Lowering context handed to op kernels
# ---------------------------------------------------------------------------

class LoweringContext:
    def __init__(self, executor: "Executor", program: Program, rng_key,
                 lod_map: Dict[str, Any]):
        self.executor = executor
        self.program = program
        self.place = executor.place
        self._rng_key = rng_key
        self.lod_map = lod_map    # var name -> lod metadata (host-side)
        # mixed-precision compute dtype for MXU-bound ops (amp.py); None =
        # full precision. Read by ops.common.mxu_cast. Level O1 restores
        # f32 after each MXU op; O2 keeps activations bf16 end-to-end.
        self.amp_dtype = getattr(program, "_amp_dtype", None)
        self.amp_level = getattr(program, "_amp_level", "O1")
        # O3 quantization mode ("int8"/"fp8", amp.py) or None; read by
        # the matmul/conv lowerings to route through quant.py
        self.quant_mode = getattr(program, "_quant_mode", None)
        # live env of the block being traced; lowerings use it to read
        # sequence-length side channels (`<var>@SEQLEN`, see seq_len()).
        self.env: Dict[str, Any] = {}
        # out var name -> lengths array (or None to clear) set by sequence
        # lowerings to override the default SEQLEN propagation in _exec_op
        self.seq_overrides: Dict[str, Any] = {}
        # internal activation-layout tags (ops/layout.py): var name ->
        # "NHWC"/"NDHWC" for values held in the TPU-preferred layout;
        # absent = canonical NCHW. Aware lowerings set tags for their
        # outputs via set_layout (collected per-op like seq_overrides).
        from .ops import layout as layout_mod
        self.layout_opt = layout_mod.LAYOUT_OPT
        self.layouts: Dict[str, str] = {}
        self.layout_overrides: Dict[str, Any] = {}

    def layout_of(self, name: str):
        return self.layouts.get(name)

    def set_layout(self, name: str, tag):
        self.layout_overrides[name] = tag

    def seq_len(self, name: str):
        """Per-sequence valid lengths [batch] for a padded sequence var, or
        None. The TPU-native stand-in for the reference's LoD offset table
        (lod_tensor.h:55): LoDTensor feeds are padded dense and their lengths
        ride along the trace as an int32 array input."""
        return self.env.get(name + SEQLEN_SUFFIX)

    def seq_len2(self, name: str):
        """Inner lengths [batch, S] for a nested (lod_level=2) sequence var,
        or None (reference lod_tensor.h:55 second offset level)."""
        return self.env.get(name + SEQLEN2_SUFFIX)

    def set_seq_len(self, name: str, lengths):
        self.seq_overrides[name] = lengths

    def set_seq_len2(self, name: str, lengths):
        self.seq_overrides[name + SEQLEN2_SUFFIX] = lengths

    def next_rng(self, op=None):
        """Deterministic per-op PRNG key. Keyed on the op's first output name
        (stable identity), NOT a call counter: the generic vjp grad kernel
        re-traces the forward lowering, and a counter would hand the re-trace
        a different key than the forward pass saw (e.g. a dropout mask that
        differs between forward and backward). Per-step variation comes from
        the run counter folded into the base key (Executor.run)."""
        seed = int(op.attr("seed", 0) or 0) if op is not None else 0
        key = self._rng_key if not seed else jax.random.key(seed)
        ident = 0
        if op is not None:
            outs = op.desc.output_arg_names()
            if outs:
                import zlib
                ident = zlib.crc32(outs[0].encode("utf-8"))
        return jax.random.fold_in(key, ident)

    def run_block(self, block_idx: int, env: Dict[str, Any]) -> Dict[str, Any]:
        """Trace a sub-block's ops against `env` (for control-flow lowerings).
        Mutates and returns env."""
        block = self.program.block(block_idx)
        for op in block.ops:
            self.executor._exec_op(self, op, env)
        return env


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

_EAGER = os.environ.get("PADDLE_TPU_EAGER", "0") == "1"
_CHECK_NAN_INF = os.environ.get("PADDLE_TPU_CHECK_NAN_INF", "0") == "1"
_BENCHMARK = os.environ.get("PADDLE_TPU_BENCHMARK", "0") == "1"
_VLOG_LEVEL = int(os.environ.get("PADDLE_TPU_VLOG", "0") or 0)
# telemetry side-fetches (program._telemetry_fetch_extra, e.g. the clip
# pass's global-norm var): each one forces a device->host read per step to
# feed its gauge — PADDLE_TPU_TELEMETRY_FETCH=0 turns them off for
# latency-critical pipelined loops
_TELEMETRY_FETCH = os.environ.get("PADDLE_TPU_TELEMETRY_FETCH", "1") == "1"
# opt-in static verification at first compile (paddle_tpu.analysis): the
# reference's compile-time InferShape story — error-severity diagnostics
# raise errors.ProgramVerifyError BEFORE tracing, pointing at the op's
# Python creation site instead of a JAX traceback
_VERIFY = os.environ.get("PADDLE_TPU_VERIFY", "0") == "1"


_WARNED_CPU_SCAN_CONV = False


def _maybe_warn_cpu_scan_conv(device, program, steps):
    """Warn ONCE when a multi-step run_steps window is about to lower a
    conv backward inside lax.scan on the CPU backend: XLA:CPU runs
    grad-conv under scan ~60x slower than the same ops dispatched per
    step (the PR 5 windowed-dispatch caveat, previously documented only
    in CHANGES.md). Correctness is unaffected — tests stay on the
    windowed path — but a CPU training loop that cares about wall time
    should use per-step run() or a TPU backend."""
    global _WARNED_CPU_SCAN_CONV
    if _WARNED_CPU_SCAN_CONV or steps <= 1:
        return
    plat = getattr(device, "platform", None)
    if plat is None:
        plat = jax.default_backend()
    if plat != "cpu":
        return
    types = {o.type for o in program.global_block().ops}
    if not (types & {"conv2d_grad", "depthwise_conv2d_grad", "conv3d_grad",
                     "conv2d_transpose_grad"}):
        return
    _WARNED_CPU_SCAN_CONV = True
    import warnings
    warnings.warn(
        "run_steps is lowering a conv backward inside a lax.scan window "
        "on the XLA:CPU backend — known ~60x slower than per-step "
        "dispatch (see CHANGES.md, windowed dispatch caveat). Use "
        "exe.run() per step or steps=1 for CPU training wall time; TPU "
        "backends are unaffected.", RuntimeWarning, stacklevel=3)


def _vlog_level() -> int:
    """Live verbosity: the flags registry re-reads PADDLE_TPU_VLOG on every
    call, so flags.set("vlog", n) changes vlog() output at runtime (the
    import-time _VLOG_LEVEL snapshot is only the fallback if the registry
    is unavailable mid-interpreter-teardown)."""
    try:
        return int(flags_mod.get("vlog"))
    except Exception:
        return _VLOG_LEVEL


def vlog(level: int, msg: str):
    """glog-style leveled logging (reference VLOG; enable with
    PADDLE_TPU_VLOG=<level> or flags.set("vlog", n) at runtime)."""
    if level <= _vlog_level():
        import datetime
        ts = datetime.datetime.now().strftime("%H:%M:%S.%f")[:-3]
        print(f"V{level} {ts} paddle_tpu] {msg}", file=sys.stderr)

# FP-exception trapping (reference TrainerMain.cpp:49 feenableexcept
# FE_INVALID|FE_DIVBYZERO|FE_OVERFLOW): the XLA-world equivalent is
# jax's debug-nans mode — any op producing NaN/Inf raises at the op that
# made it (de-optimizes to op-by-op execution, debug only).
if os.environ.get("PADDLE_TPU_TRAP_FP", "0") == "1":
    jax.config.update("jax_debug_nans", True)
    jax.config.update("jax_debug_infs", True)

# op-coverage recorder: every executed op type lands in the in-process set
# (tests/test_zz_op_coverage.py asserts full-registry coverage at the end of
# a suite run); PADDLE_TPU_RECORD_OPS additionally appends to a file for
# cross-process reports (tools/op_coverage.py)
_RECORD_OPS_PATH = os.environ.get("PADDLE_TPU_RECORD_OPS")
_RECORDED_OPS = set()


def _record_op(op_type: str):
    if op_type not in _RECORDED_OPS:
        _RECORDED_OPS.add(op_type)
        if _RECORD_OPS_PATH:
            with open(_RECORD_OPS_PATH, "a") as f:
                f.write(op_type + "\n")

SEQLEN_SUFFIX = "@SEQLEN"
SEQLEN2_SUFFIX = "@SEQLEN2"   # inner lengths [B, S] of nested (level-2) LoD

# ops with a native SelectedRows (sparse-rows) kernel; everything else
# receives densified gradients (counted: sparse_densify_fallback_total).
# The reference registers SelectedRows variants for sum/sgd/adam
# (sum_op.cc, sgd_op.h, adam_op.h); momentum is a deliberate extension so
# the default CNN optimizer also keeps embedding grads sparse. The
# optimizer entries come from the sparse-capable table in
# ops/sparse_ops.py, which tools/check_registry.py pins against the
# actual lowerings. fused_sparse_* are the trace-time scatter-apply
# buckets (ops/fusion.py) — their Grad inputs must cross the boundary
# still sparse for the member kernels to re-execute.
_SPARSE_AWARE_OPS = frozenset(
    {"sum"} | set(sparse_ops_mod.SPARSE_APPLY_OPS)
    | {"fused_sparse_" + t for t in sparse_ops_mod.SPARSE_APPLY_OPS})


def _bucket_len(n: int) -> int:
    """Round a max sequence length up to a bucket boundary so XLA sees a
    small set of static shapes instead of one per batch (SURVEY.md §7:
    'bucketing + dense speed'): powers of two up to 64, multiples of 64 after."""
    if n <= 8:
        return 8
    b = 8
    while b < n and b < 64:
        b *= 2
    return b if b >= n else ((n + 63) // 64) * 64


def pack_to_padded(flat: np.ndarray, lod: List[List[int]]):
    """Packed [sum_len, ...] rows + LoD offsets -> padded dense + lengths:
    level-1 gives ([batch, T, ...], lengths [batch], None); level-2 nested
    sequences (reference lod_tensor.h:55, RecurrentGradientMachine.h:32)
    give ([batch, S, T, ...], outer lengths [batch], inner lengths
    [batch, S]). The dense/padded layout is the XLA-friendly equivalent of
    the reference's zero-padding-free packed LoDTensor."""
    assert len(lod) in (1, 2), "lod_level must be 1 or 2"
    if len(lod) == 1:
        offs = np.asarray(lod[0], dtype=np.int64)
        lengths = np.diff(offs).astype(np.int32)
        bsz = len(lengths)
        t = _bucket_len(int(lengths.max()) if bsz else 1)
        padded = np.zeros((bsz, t) + tuple(flat.shape[1:]), dtype=flat.dtype)
        if bsz and len(flat):
            # vectorized scatter: row r of flat lands at
            # [batch(r), r - start(batch(r))] — no per-sample Python loop in
            # the feed path (VERDICT r2 weak #7)
            batch_idx = np.repeat(np.arange(bsz), lengths)
            time_idx = np.arange(offs[-1]) - np.repeat(offs[:-1], lengths)
            padded[batch_idx, time_idx] = flat[: offs[-1]]
        return padded, lengths, None
    outer, inner = lod
    outer = np.asarray(outer, dtype=np.int64)
    inner = np.asarray(inner, dtype=np.int64)
    outer_lens = np.diff(outer).astype(np.int32)
    inner_lens_flat = np.diff(inner).astype(np.int32)
    bsz = len(outer_lens)
    s_max = _bucket_len(int(outer_lens.max()) if bsz else 1)
    t_max = _bucket_len(int(inner_lens_flat.max())
                        if len(inner_lens_flat) else 1)
    padded = np.zeros((bsz, s_max, t_max) + tuple(flat.shape[1:]),
                      dtype=flat.dtype)
    inner_lens = np.zeros((bsz, s_max), dtype=np.int32)
    if bsz and len(inner_lens_flat):
        n_seq = len(inner_lens_flat)
        seq_batch = np.repeat(np.arange(bsz), outer_lens)       # [n_seq]
        seq_pos = np.arange(n_seq) - np.repeat(outer[:-1], outer_lens)
        inner_lens[seq_batch, seq_pos] = inner_lens_flat
        total = int(inner[-1])
        if total:
            row_seq = np.repeat(np.arange(n_seq), inner_lens_flat)
            row_b = seq_batch[row_seq]
            row_s = seq_pos[row_seq]
            row_t = np.arange(total) - np.repeat(inner[:-1], inner_lens_flat)
            padded[row_b, row_s, row_t] = flat[:total]
    return padded, outer_lens, inner_lens


def padded_to_pack(padded: np.ndarray, lengths: np.ndarray,
                   inner_lengths: Optional[np.ndarray] = None):
    """Inverse of pack_to_padded: padded + lengths -> packed rows + LoD
    offsets (for fetch-side LoDTensor reconstruction); with inner_lengths
    the input is a nested [B, S, T, ...] batch and a 2-level LoD comes
    back."""
    lengths = np.asarray(lengths, dtype=np.int64)
    if inner_lengths is None:
        bsz = len(lengths)
        offs = np.concatenate([[0], np.cumsum(lengths)])
        if bsz == 0 or offs[-1] == 0:
            return padded[:0, 0], [offs.tolist()]
        batch_idx = np.repeat(np.arange(bsz), lengths)
        time_idx = np.arange(offs[-1]) - np.repeat(offs[:-1], lengths)
        return padded[batch_idx, time_idx], [offs.tolist()]
    inner_lengths = np.asarray(inner_lengths, dtype=np.int64)
    bsz = len(lengths)
    outer_offs = np.concatenate([[0], np.cumsum(lengths)])
    n_seq = int(outer_offs[-1])
    if n_seq == 0:
        return padded[:0, 0, 0], [outer_offs.tolist(), [0]]
    seq_batch = np.repeat(np.arange(bsz), lengths)
    seq_pos = np.arange(n_seq) - np.repeat(outer_offs[:-1], lengths)
    seq_lens = inner_lengths[seq_batch, seq_pos]                # [n_seq]
    inner_offs = np.concatenate([[0], np.cumsum(seq_lens)])
    total = int(inner_offs[-1])
    if total == 0:
        return padded[:0, 0, 0], [outer_offs.tolist(), inner_offs.tolist()]
    row_seq = np.repeat(np.arange(n_seq), seq_lens)
    row_t = np.arange(total) - np.repeat(inner_offs[:-1], seq_lens)
    return (padded[seq_batch[row_seq], seq_pos[row_seq], row_t],
            [outer_offs.tolist(), inner_offs.tolist()])


def _aval_of(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        arr = np.asarray(x)
        shape, dtype = arr.shape, arr.dtype
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _hlo_supplier(fn, feed_vals, state_vals, rng_counter):
    """Zero-arg lazy supplier of the block's AOT-compiled executable for
    the profiler's per-op device table (.as_text() gives the optimized HLO
    the attribution joins against, .cost_analysis() the XLA flop count the
    analytic cost model cross-checks). Captures ONLY avals
    (shapes/dtypes), never the arrays — state buffers are donated and must
    not be kept alive. supply() is an AOT lower().compile(): a REAL
    recompile unless the persistent compilation cache covers it, which is
    why the profiler caps its supplier registry and only traced sessions
    pay this — at stop_profiler, never inside the timed region."""
    avals = jax.tree_util.tree_map(_aval_of,
                                   (feed_vals, state_vals, rng_counter))

    def supply():
        return fn.lower(*avals).compile()

    return supply


# Observers notified as (op, ins, outs) for every op lowered by _exec_op —
# ins/outs are {slot: [tracer|None]}. Installed only for the duration of an
# abstract trace (roofline.program_cost runs jax.eval_shape with one) so
# the analytic cost model sees concrete per-op shapes/dtypes instead of
# the ProgramDesc's -1 batch dims. Empty in normal execution: the per-op
# overhead is one falsy check at trace time, nothing at run time.
_op_observers: List = []


def _cost_supplier(executor, program, feed_vals, state_vals, window=False):
    """Zero-arg lazy supplier of the analytic per-op cost table
    (roofline.program_cost) for the same compiled block _hlo_supplier
    describes. Same discipline: captures only avals. window=True strips
    the leading [K] steps axis off each feed so the table is per-step."""
    feed_avals = {n: _aval_of(v) for n, v in feed_vals.items()}
    if window:
        feed_avals = {n: jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
                      for n, a in feed_avals.items()}
    state_avals = {n: _aval_of(v) for n, v in state_vals.items()}

    def cost():
        from . import roofline
        return roofline.program_cost(executor, program, feed_avals,
                                     state_avals)

    return cost


@jax.jit
def _finite_all(leaves):
    """ONE fused finiteness reduction over every checked tensor of a step:
    the jit-path check_nan_inf used to `np.asarray` each fetch and state
    item — a device->host sync per tensor; this reduces them all on-device
    and costs a single scalar readback. Trace-cached per aval signature."""
    return functools.reduce(
        jnp.logical_and, (jnp.all(jnp.isfinite(x)) for x in leaves),
        jnp.asarray(True))


def _accepts_sparse_slots(reader) -> bool:
    """Whether a run_steps reader's next_window takes the emb_cache
    sparse_slots hook (reader.pipeline.DoubleBufferedFeeder does;
    user-supplied readers may predate it)."""
    import inspect
    try:
        return "sparse_slots" in inspect.signature(
            reader.next_window).parameters
    except (TypeError, ValueError):
        return False


class _WindowUnsupported(Exception):
    """Raised at trace time when a program feature (sequence/LoD fetches,
    shape-changing state) cannot ride through the lax.scan window; the
    executor falls back to the per-step path."""


class _CompiledBlock:
    def __init__(self, fn, state_names, feed_names, fetch_names, program):
        self.fn = fn
        self.state_names = state_names
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        # strong ref: the cache key uses id(program), which stays valid only
        # while the program object is alive
        self.program = program
        # feed (name, shape, dtype) signatures already traced by self.fn:
        # the executor's view of jax.jit's retrace cache, kept so telemetry
        # can name the signature that caused a cache miss (a retrace the
        # executor-level cache key — names only, no shapes — cannot see)
        self.seen_sigs: set = set()
        self.last_sig = None


class Executor:
    def __init__(self, place: Optional[Place] = None):
        self.place = place if place is not None else TPUPlace(0)
        self.device = place_device(self.place)
        self._cache: Dict[Tuple, _CompiledBlock] = {}
        self._analysis_cache: Dict[Tuple, Tuple] = {}

    # --- public API ---------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed: Optional[Dict] = None,
            fetch_list: Optional[Sequence] = None, feed_var_name: str = "feed",
            fetch_var_name: str = "fetch", scope: Optional[Scope] = None,
            return_numpy: bool = True, use_program_cache: bool = True,
            use_jit: Optional[bool] = None):
        program = program if program is not None else default_main_program()
        # hang watchdog: a None fast-path unless sentinel.start() ran
        from . import sentinel as sentinel_mod
        _tok = sentinel_mod.arm_dispatch(telemetry.program_label(program))
        try:
            return self._run_impl(program, feed, fetch_list, feed_var_name,
                                  fetch_var_name, scope, return_numpy,
                                  use_program_cache, use_jit)
        except Exception as e:
            # flight-recorder crash hook: a no-op unless the recorder is
            # enabled (inspector.enable_flight_recorder or the
            # PADDLE_TPU_FLIGHT_RECORDER flag); writes the JSON crash report
            # before the exception propagates
            from . import inspector as inspector_mod
            inspector_mod.notify_crash(self, program, e)
            raise
        finally:
            sentinel_mod.disarm_dispatch(_tok)

    def run_steps(self, program: Optional[Program] = None, feed_window=None,
                  *, reader=None, steps: Optional[int] = None,
                  fetch_list: Optional[Sequence] = None,
                  scope: Optional[Scope] = None, return_numpy: bool = True,
                  fetch_mode: str = "last", use_program_cache: bool = True,
                  use_jit: Optional[bool] = None):
        """Run K training steps in ONE host dispatch: the per-step compiled
        function wrapped in a jax.lax.scan over a device-stacked window of K
        batches, with persistable state donated across the whole window.
        One Python round-trip, one scope write-back, one telemetry record
        per K steps — the fused-loop answer to the reference's
        ParallelExecutor + double_buffer amortization
        (operators/reader/create_double_buffer_reader_op.cc).

        feed_window: a list of K per-step feed dicts, or a dict of arrays
        pre-stacked with a leading [K] axis. reader: an object with
        `next_window(k, device=...)` (reader.pipeline.DoubleBufferedFeeder)
        pulled instead of feed_window; requires `steps`. fetch_mode: 'last'
        (default) returns the final step's fetches, 'stack' a [K, ...] stack
        per fetch, 'mean' the window mean (e.g. for loss curves).

        Bitwise parity with K sequential run() calls is test-enforced
        (tests/test_run_steps.py): the scan carries the same uint32 rng
        counter the per-step path folds in, and `__rng_counter__` advances
        atomically by K only after the window succeeds.

        Falls back to K per-step run() calls — same results, per-step
        dispatch cost — in eager mode, when check_nan_inf or inspector
        probes need per-step attribution, and for LoD/sequence feeds or
        state (the padded repack is per-batch host work). Telemetry
        side-fetch gauges (_telemetry_fetch_extra) are skipped on the
        window path: they are a per-step observability feature."""
        program = program if program is not None else default_main_program()
        from . import sentinel as sentinel_mod
        _tok = sentinel_mod.arm_dispatch(telemetry.program_label(program))
        try:
            return self._run_steps_impl(
                program, feed_window, reader, steps, fetch_list, scope,
                return_numpy, fetch_mode, use_program_cache, use_jit)
        except Exception as e:
            from . import inspector as inspector_mod
            inspector_mod.notify_crash(self, program, e)
            raise
        finally:
            sentinel_mod.disarm_dispatch(_tok)

    def _run_steps_impl(self, program, feed_window, reader, steps,
                        fetch_list, scope, return_numpy, fetch_mode,
                        use_program_cache, use_jit):
        if fetch_mode not in ("last", "stack", "mean"):
            raise ValueError(f"fetch_mode must be last|stack|mean, "
                             f"got {fetch_mode!r}")
        scope = scope if scope is not None else global_scope()
        emb_cache = getattr(program, "_emb_cache", None)
        if reader is not None:
            if feed_window is not None:
                raise ValueError("pass feed_window or reader, not both")
            if steps is None:
                raise ValueError("reader windows need an explicit steps=K")
            # may raise StopIteration at end of pass — the drain signal.
            # With a hot-row cache active, ask the feeder to keep the
            # cached-table id slots host-side and hand back their
            # unique-id union (sparse_slots) — the ids remap to cache
            # slots below, so device_put-ing the raw ids would waste the
            # transfer and force a sync for the remap.
            if emb_cache is not None and _accepts_sparse_slots(reader):
                feed_window, _uniq = reader.next_window(
                    steps, device=self.device,
                    sparse_slots=emb_cache.feed_id_names())
            else:
                feed_window = reader.next_window(steps, device=self.device)
        if feed_window is None:
            raise ValueError("run_steps needs feed_window= or reader=")
        stacked, per_step, steps, lod_reason = self._normalize_window(
            feed_window, steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")

        prog_label = telemetry.program_label(program)
        place_label = f"{type(self.place).__name__}:{self.place.device_id}"
        jit_mode = (not _EAGER) if use_jit is None else use_jit
        check_nan = _CHECK_NAN_INF or flags_mod.get("check_nan_inf")
        reason = lod_reason
        if steps == 1:
            reason = reason or "single_step"
        elif not jit_mode:
            reason = reason or "eager"
        elif check_nan:
            reason = reason or "check_nan_inf"
        elif getattr(program, "_probe_sites", None):
            reason = reason or "probes"
        if reason is None:
            # state-side LoD rejection: packed sequence state needs a
            # host-side repack per step
            fed = set(stacked)
            for n in self._external_inputs(program, fed, scope):
                v = scope.find_var(n)
                if isinstance(v, LoDTensor) and v.lod:
                    reason = "lod_state"
                    break
        if reason is None:
            _maybe_warn_cpu_scan_conv(self.device, program, steps)
            try:
                # emb_cache: remap the WHOLE window's ids to cache slots
                # in one residency transaction (every scanned step runs
                # against the same slab, so the union must be resident
                # at once). Done only on the window path: the per-step
                # fallback below re-derives feeds from the raw `stacked`
                # and each run() call remaps its own step — remapping
                # twice would read slot ids as global row ids.
                win_stacked = (emb_cache.prepare_feed(stacked)
                               if emb_cache is not None else stacked)
                return self._run_steps_window(
                    program, win_stacked, steps, fetch_list, scope,
                    return_numpy, fetch_mode, use_program_cache,
                    prog_label, place_label)
            except _WindowUnsupported as e:
                reason = "trace_unsupported"
                vlog(1, f"run_steps window unsupported, falling back: {e}")
        if steps > 1:
            telemetry.counter(
                "executor_window_fallback_total",
                "run_steps calls served by the per-step path",
                labels=("program", "reason")).labels(
                    program=prog_label, reason=reason).inc()
        if per_step is None:
            per_step = [{n: v[i] for n, v in stacked.items()}
                        for i in range(steps)]
        return self._run_steps_fallback(
            program, per_step, fetch_list, scope, return_numpy, fetch_mode,
            use_program_cache, use_jit)

    @staticmethod
    def _normalize_window(feed_window, steps):
        """-> (stacked feed dict or None-if-LoD, per-step feed list or None,
        K, lod-fallback reason or None). A list of per-step feed dicts
        stacks host-side; a pre-stacked dict (leading [K] axis on every
        leaf, e.g. from DoubleBufferedFeeder.next_window) passes through."""
        if isinstance(feed_window, dict):
            if not feed_window:
                raise ValueError("feed_window dict is empty")
            ks = set()
            for n, v in feed_window.items():
                if isinstance(v, LoDTensor):
                    raise ValueError(
                        f"pre-stacked feed_window entry '{n}' is a "
                        f"LoDTensor; pass a list of per-step feed dicts "
                        f"so the executor can fall back per-step")
                shape = getattr(v, "shape", None)
                if not shape:
                    raise ValueError(
                        f"feed_window entry '{n}' has no leading steps "
                        f"axis (shape {shape})")
                ks.add(int(shape[0]))
            if len(ks) != 1:
                raise ValueError(
                    f"feed_window leading dims disagree: {sorted(ks)}")
            k = ks.pop()
            if steps is not None and steps != k:
                raise ValueError(
                    f"steps={steps} but feed_window leading dim is {k}")
            return dict(feed_window), None, k, None
        per_step = list(feed_window)
        if not per_step:
            raise ValueError("feed_window list is empty")
        if steps is not None and steps != len(per_step):
            raise ValueError(
                f"steps={steps} but feed_window has {len(per_step)} entries")
        names = set(per_step[0])
        if any(set(f) != names for f in per_step[1:]):
            raise ValueError("per-step feed dicts must share the same keys")
        if any(isinstance(f[n], LoDTensor) and f[n].lod
               for f in per_step for n in names):
            return None, per_step, len(per_step), "lod_feed"
        stacked = {}
        for n in sorted(names):
            stacked[n] = np.stack([np.asarray(f[n]) for f in per_step])
        return stacked, per_step, len(per_step), None

    def _run_steps_fallback(self, program, per_step_feeds, fetch_list, scope,
                            return_numpy, fetch_mode, use_program_cache,
                            use_jit):
        """Per-step path: K sequential run() calls — identical results to
        the fused window, per-step dispatch cost. The rng counter advances
        +1 per completed step (a mid-window failure keeps the completed
        prefix, matching plain sequential training)."""
        outs = []
        for f in per_step_feeds:
            vals = self.run(program, feed=f, fetch_list=fetch_list,
                            scope=scope, return_numpy=return_numpy,
                            use_program_cache=use_program_cache,
                            use_jit=use_jit)
            if fetch_mode == "last":
                outs = vals
            else:
                outs.append(vals)
        if fetch_mode == "last":
            return outs
        cols = list(zip(*outs)) if outs else []
        if fetch_mode == "stack":
            return [np.stack([np.asarray(v) for v in col]) for col in cols]
        return [np.mean(np.stack([np.asarray(v) for v in col]), axis=0)
                for col in cols]

    def _run_steps_window(self, program, stacked, steps, fetch_list, scope,
                          return_numpy, fetch_mode, use_program_cache,
                          prog_label, place_label):
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in list(fetch_list or [])]
        feed_vals = {n: (v if isinstance(v, jax.Array) else np.asarray(v))
                     for n, v in stacked.items()}
        state_names = self._external_inputs(program, set(feed_vals), scope)
        persist_out = self._persistable_outputs(program)
        missing = [n for n in state_names if scope.find_var(n) is None]
        if missing:
            raise RuntimeError(
                f"Variables {missing} are read by the program but absent "
                f"from the scope — run the startup program first.")
        state_vals = {}
        for n in state_names:
            v = scope.find_var(n)
            if isinstance(v, LoDTensor):
                v = np.asarray(v.array())   # lod-carrying state fell back
            state_vals[n] = v
        rng_counter = scope.find_var("__rng_counter__") or 0

        state_keys = sorted(state_vals)
        key = (id(program), getattr(program, "_version", 0),
               tuple(sorted(feed_vals)), tuple(fetch_names),
               tuple(state_keys), self.place,
               getattr(program, "_amp_dtype", None),
               getattr(program, "_amp_level", "O1"),
               program.random_seed, "window", steps, fetch_mode,
               dynamics_mod.cache_token(program),
               quant_mod.cache_token(program))
        compiled = self._cache.get(key) if use_program_cache else None
        if compiled is None:
            compiled = self._compile_window(
                program, state_keys, sorted(feed_vals), fetch_names,
                persist_out, {}, steps, fetch_mode)
            if use_program_cache:
                self._cache[key] = compiled
        from . import profiler as profiler_mod
        if profiler_mod.wants_device_table() and \
                not profiler_mod.has_hlo_supplier(id(compiled.fn)):
            # run_steps registers its cost analysis too: the fused window
            # is the production training path, and the MFU campaign needs
            # attribution exactly there (ISSUE 6 tentpole)
            profiler_mod.register_hlo_supplier(
                id(compiled.fn),
                _hlo_supplier(compiled.fn, feed_vals, state_vals,
                              np.uint32(rng_counter)),
                _cost_supplier(self, program, feed_vals, state_vals,
                               window=True))

        sig = telemetry.signature_of(feed_vals)
        new_sig = sig not in compiled.seen_sigs
        compile_before = telemetry.jax_compile_seconds()
        run_t0 = time.perf_counter()
        try:
            with jax.default_device(self.device):
                from . import profiler as profiler_mod
                with profiler_mod.record("executor_run(window)"):
                    fetch_vals, new_state = compiled.fn(
                        feed_vals, state_vals, np.uint32(rng_counter))
                    if profiler_mod.is_active():
                        jax.block_until_ready((fetch_vals, new_state))
        except _WindowUnsupported:
            self._cache.pop(key, None)
            raise
        except TypeError as e:
            if "carry" in str(e):
                # lax.scan rejected the carry: the program changes a state
                # aval across steps (shape/dtype drift) — per-step territory
                self._cache.pop(key, None)
                raise _WindowUnsupported(str(e)) from e
            raise
        except Exception as e:
            oom = memory_mod.maybe_oom_error(
                self, program, prog_label, e, feed_vals, state_vals)
            if oom is not None:
                raise oom from e
            raise
        run_dt = time.perf_counter() - run_t0
        compile_s = telemetry.jax_compile_seconds() - compile_before
        cache_status = "miss" if new_sig else "hit"
        if new_sig:
            cause = ("first_compile" if not compiled.seen_sigs
                     else "signature_change")
            compiled.seen_sigs.add(sig)
            telemetry.counter(
                "executor_compiles_total", "block traces/compiles",
                labels=("program", "place")).labels(
                    program=prog_label, place=place_label).inc()
            telemetry.counter(
                "executor_compile_seconds_total",
                "XLA compile wall seconds spent inside Executor.run",
                labels=("program", "place")).labels(
                    program=prog_label, place=place_label).inc(compile_s)
            telemetry.log_event(
                "compile", program=prog_label, place=place_label,
                cause=cause, seconds=compile_s, window_steps=steps,
                signature=[list(s) for s in sig])
        else:
            telemetry.counter(
                "executor_cache_hits_total",
                "runs served by an already-traced signature",
                labels=("program", "place")).labels(
                    program=prog_label, place=place_label).inc()
        compiled.last_sig = sig

        # window succeeded: counter commit is atomic for all K steps
        dyn_stats = new_state.pop(dynamics_mod.STATE_KEY, None)
        scope.set_var("__rng_counter__", rng_counter + steps)
        for n, v in new_state.items():
            scope.set_var(n, v)
        if dyn_stats is not None:
            dynamics_mod.on_window(program, prog_label, dyn_stats,
                                   int(rng_counter), steps)

        telemetry.counter(
            "executor_runs_total", "Executor.run calls",
            labels=("program", "place", "mode")).labels(
                program=prog_label, place=place_label, mode="window").inc()
        telemetry.counter(
            "executor_steps_total",
            "training/eval steps executed (a run_steps window counts K)",
            labels=("program", "place")).labels(
                program=prog_label, place=place_label).inc(steps)
        telemetry.histogram(
            "executor_run_seconds",
            "Executor.run wall seconds (dispatch-only unless profiling "
            "forces device sync)", labels=("program", "mode")).labels(
                program=prog_label, mode="window").observe(run_dt)
        telemetry.gauge(
            "executor_last_step_seconds",
            "wall seconds of the most recent executor step (per-step "
            "average for run_steps windows) — fleet skew input").set(
                max(run_dt - compile_s, 0.0) / steps)
        if self._analysis(program)[3]:
            telemetry.counter(
                "optimizer_steps_total",
                "runs of programs carrying optimizer-role ops",
                labels=("program",)).labels(program=prog_label).inc(steps)
        telemetry.log_event(
            "run_window", program=prog_label, place=place_label,
            mode="window", steps=steps, seconds=run_dt,
            per_step_seconds=run_dt / steps, compile_s=compile_s,
            execute_s=max(run_dt - compile_s, 0.0), cache=cache_status,
            donated=len(state_vals), feeds=len(feed_vals),
            fetches=len(fetch_names))
        if tracing_mod.enabled():
            # retroactive window span from the wall time already measured
            # (perf_counter and monotonic share CLOCK_MONOTONIC on linux;
            # we re-anchor on monotonic to keep one trace timebase)
            t_end = time.monotonic()
            sp = tracing_mod.record_span(
                "run_steps_window", t_end - run_dt, t_end,
                attrs={"program": prog_label, "place": place_label,
                       "steps": steps, "cache": cache_status})
            if new_sig and compile_s > 0.0:
                tracing_mod.record_span(
                    "compile", t_end - run_dt,
                    min(t_end - run_dt + compile_s, t_end), parent=sp,
                    attrs={"cause": cause, "seconds": compile_s})

        hbm_sample = None
        try:
            hbm_sample = memory_mod.on_run(
                self, program, prog_label, feed_vals, state_vals)
        except Exception:
            hbm_sample = None
        from . import inspector as inspector_mod
        if inspector_mod.flight_enabled():
            # ONE flight-recorder entry per window, per-step seconds
            # derived from the window wall clock
            inspector_mod.record_step(program, prog_label, {
                "place": place_label, "mode": "window", "steps": steps,
                "seconds": run_dt, "per_step_seconds": run_dt / steps,
                "compile_s": compile_s, "cache": cache_status,
                "feeds": len(feed_vals), "fetches": len(fetch_names),
                "rng_counter": int(rng_counter),
                "hbm_bytes_in_use": (hbm_sample or {}).get("bytes_in_use"),
                "hbm_peak_bytes": (hbm_sample or {}).get("peak_bytes"),
            })
        return [np.asarray(v) if return_numpy else v for v in fetch_vals]

    def static_memory_analysis(self, program=None, feed=None,
                               fetch_list=None, scope=None, top_k=8):
        """Compile-only memory footprint of `program` under `feed`: the
        block is traced and compiled exactly as run() would (same
        donation, shardings and state gathering) but never executed, so
        no step runs and no real buffers are allocated — feed values may
        be jax.ShapeDtypeStructs, letting what-if probes ask about batch
        sizes that could never fit in host or device memory. Returns the
        memory.ProgramMemory record (also kept in memory.records())."""
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in list(fetch_list or [])]
        feed_vals, lod_map = {}, {}
        for name, val in dict(feed or {}).items():
            if isinstance(val, LoDTensor):
                lod_map[name] = val.lod
                arr = np.asarray(val.array())
                if val.lod:
                    arr, lengths, inner = pack_to_padded(arr, val.lod)
                    feed_vals[name + SEQLEN_SUFFIX] = lengths
                    if inner is not None:
                        feed_vals[name + SEQLEN2_SUFFIX] = inner
                feed_vals[name] = arr
            elif hasattr(val, "shape") and hasattr(val, "dtype"):
                feed_vals[name] = val   # array or aval, never materialized
            else:
                feed_vals[name] = np.asarray(val)
        state_names = self._external_inputs(program, set(feed_vals), scope)
        missing = [n for n in state_names if scope.find_var(n) is None]
        if missing:
            raise RuntimeError(
                f"Variables {missing} are read by the program but absent "
                f"from the scope — run the startup program first.")
        state_vals = {}
        for n in state_names:
            v = scope.find_var(n)
            if isinstance(v, LoDTensor):
                lod_map[n] = v.lod
                arr = np.asarray(v.array())
                if v.lod:
                    arr, lengths, inner = pack_to_padded(arr, v.lod)
                    state_vals[n + SEQLEN_SUFFIX] = lengths
                    if inner is not None:
                        state_vals[n + SEQLEN2_SUFFIX] = inner
                v = arr
            state_vals[n] = v
        compiled = self._compile(
            program, sorted(state_vals), sorted(feed_vals), fetch_names,
            self._persistable_outputs(program), lod_map)
        return memory_mod.analyze(
            compiled.fn, feed_vals, state_vals,
            scope.find_var("__rng_counter__") or 0,
            program=telemetry.program_label(program),
            place=f"{type(self.place).__name__}:{self.place.device_id}",
            top_k=top_k)

    def _run_impl(self, program, feed, fetch_list, feed_var_name,
                  fetch_var_name, scope, return_numpy, use_program_cache,
                  use_jit):
        feed = dict(feed or {})
        # program-bound reader pipelines (layers.read_file): when the caller
        # gives no explicit feed for the reader vars, pull the next
        # (prefetched) batch — the executor-side half of the reference's
        # reader ops (operators/reader/*.cc). Raises EOFException when a
        # pass ends, matching the reference's drain loop idiom.
        for reader, names in getattr(program, "_pipeline_readers", []):
            fed = [n for n in names if n in feed]
            if fed:
                if len(fed) != len(names):
                    raise ValueError(
                        f"Reader variables {sorted(set(names) - set(fed))} "
                        f"are not in the feed but their sibling(s) {fed} "
                        f"are; feed all of a reader's outputs or none "
                        f"(pipeline pull is all-or-nothing)")
                continue
            batch_vals = reader.next_batch(self.device)
            feed.update(dict(zip(names, batch_vals)))
        # beyond-HBM hot-row cache (parallel/emb_cache.py): make the fed
        # ids of cached tables resident and remap them to cache-slot
        # indices, so lookup_table and the scatter-apply optimizers run
        # against the fixed-size device slab with static shapes
        emb_cache = getattr(program, "_emb_cache", None)
        if emb_cache is not None:
            feed = emb_cache.prepare_feed(feed)
        fetch_list = list(fetch_list or [])
        scope = scope if scope is not None else global_scope()
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in fetch_list]
        jit_mode = (not _EAGER) if use_jit is None else use_jit

        prog_label = telemetry.program_label(program)
        place_label = f"{type(self.place).__name__}:{self.place.device_id}"
        # telemetry side-fetches (gauge name -> var name), e.g. the global
        # norm the clip pass marked: fetched alongside the user's list (so
        # they share the compiled block) and popped before values return
        n_user_fetch = len(fetch_names)
        extra_fetch = []
        if _TELEMETRY_FETCH:
            marked = getattr(program, "_telemetry_fetch_extra", None)
            if marked:
                extra_fetch = [(m, n) for m, n in sorted(marked.items())
                               if n not in fetch_names]
                fetch_names = fetch_names + [n for _, n in extra_fetch]

        # inspector probes (inspector.instrument / GradientAudit): their
        # stat vectors are fetched with the user's list, so the probed step
        # stays one jitted computation and one device round-trip. Replay
        # programs built by the inspector itself (_inspector_internal) fetch
        # explicitly and skip all recording/raising to avoid recursion.
        internal_run = bool(getattr(program, "_inspector_internal", False))
        probe_sites = getattr(program, "_probe_sites", None) or None
        if probe_sites and not internal_run:
            fetch_names = fetch_names + [s.stat_var for s in probe_sites]
        else:
            probe_sites = None
        # check_nan_inf is live: the import-time snapshot (kept because
        # tests/tools monkeypatch it) OR the flag registry's current value,
        # so flags.set("check_nan_inf", True) takes effect mid-session
        check_nan = (_CHECK_NAN_INF or flags_mod.get("check_nan_inf")) \
            and not internal_run

        # Normalize feeds. LoDTensor feeds with a LoD become padded dense
        # arrays plus a `<name>@SEQLEN` lengths input (pack_to_padded) — the
        # XLA-friendly LoD emulation; plain arrays pass through.
        feed_vals, lod_map = {}, {}
        for name, val in feed.items():
            if isinstance(val, LoDTensor):
                lod_map[name] = val.lod
                arr = np.asarray(val.array())
                if val.lod:
                    arr, lengths, inner = pack_to_padded(arr, val.lod)
                    feed_vals[name + SEQLEN_SUFFIX] = lengths
                    if inner is not None:
                        feed_vals[name + SEQLEN2_SUFFIX] = inner
                feed_vals[name] = arr
            else:
                feed_vals[name] = np.asarray(val) if not isinstance(
                    val, jax.Array) else val

        block = program.global_block()
        state_names = self._external_inputs(program, set(feed_vals), scope)
        persist_out = self._persistable_outputs(program)

        missing = [n for n in state_names if scope.find_var(n) is None]
        if missing:
            raise RuntimeError(
                f"Variables {missing} are read by the program but absent from "
                f"the scope — run the startup program first.")

        state_vals = {}
        for n in state_names:
            v = scope.find_var(n)
            if isinstance(v, LoDTensor):
                lod_map[n] = v.lod
                arr = np.asarray(v.array())
                if v.lod:
                    # same padded+SEQLEN convention as LoD feeds
                    arr, lengths, inner = pack_to_padded(arr, v.lod)
                    state_vals[n + SEQLEN_SUFFIX] = lengths
                    if inner is not None:
                        state_vals[n + SEQLEN2_SUFFIX] = inner
                v = arr
            state_vals[n] = v

        # the per-step PRNG counter is read here but only committed back to
        # the scope after the step SUCCEEDS (past the compiled call, the
        # check_nan_inf scan and the probe checks): a raising run must not
        # advance the counter, or an OOM/NonFinite retry would replay the
        # failed step under a different key
        rng_counter = scope.find_var("__rng_counter__") or 0

        state_keys = sorted(state_vals)  # incl. @SEQLEN side channels
        if jit_mode:
            key = (id(program), getattr(program, "_version", 0),
                   tuple(sorted(feed_vals)), tuple(fetch_names),
                   tuple(state_keys), self.place,
                   getattr(program, "_amp_dtype", None),
                   getattr(program, "_amp_level", "O1"),
                   # the seed folds into the compiled step (see _compile),
                   # so changing program.random_seed must recompile
                   program.random_seed,
                   dynamics_mod.cache_token(program),
                   quant_mod.cache_token(program))
            compiled = self._cache.get(key) if use_program_cache else None
            if compiled is None:
                compiled = self._compile(program, state_keys, sorted(feed_vals),
                                         fetch_names, persist_out, lod_map)
                if use_program_cache:
                    self._cache[key] = compiled
            from . import profiler as profiler_mod
            if profiler_mod.wants_device_table() and \
                    not profiler_mod.has_hlo_supplier(id(compiled.fn)):
                # once per compiled block: building the aval pytree every
                # step would inflate the host timings being measured
                profiler_mod.register_hlo_supplier(
                    id(compiled.fn),
                    _hlo_supplier(compiled.fn, feed_vals, state_vals,
                                  np.uint32(rng_counter)),
                    _cost_supplier(self, program, feed_vals, state_vals))
            sig = telemetry.signature_of(feed_vals)
            new_sig = sig not in compiled.seen_sigs
            compile_before = telemetry.jax_compile_seconds()
            run_t0 = time.perf_counter()
            try:
                with jax.default_device(self.device):
                    with profiler_mod.record("executor_run(jit)"):
                        fetch_vals, fetch_lens, new_state = compiled.fn(
                            feed_vals, state_vals, np.uint32(rng_counter))
                        if profiler_mod.is_active():
                            # async dispatch returns futures; force execution
                            # inside the timed scope so the event measures the
                            # step, not the enqueue (only when profiling)
                            jax.block_until_ready((fetch_vals, new_state))
            except Exception as e:
                # OOM forensics: a raw RESOURCE_EXHAUSTED becomes a
                # structured errors.OOMError (breakdown, top live buffers,
                # donation losses, suggestions) before the crash-report
                # hook in run() sees it
                oom = memory_mod.maybe_oom_error(
                    self, program, prog_label, e, feed_vals, state_vals)
                if oom is not None:
                    raise oom from e
                raise
            run_dt = time.perf_counter() - run_t0
            # the dynamics stats row leaves new_state immediately: its
            # off-period NaN filler must never reach the check_nan scan or
            # the scope writeback (recorded only after the step commits)
            dyn_stats = new_state.pop(dynamics_mod.STATE_KEY, None)
            # compile-vs-execute split: XLA's own backend_compile events
            # (jax.monitoring) accumulated across the call — catches the
            # jit retraces the executor cache key cannot see
            compile_s = telemetry.jax_compile_seconds() - compile_before
            mode, donated = "jit", len(state_vals)
            cache_status = "miss" if new_sig else "hit"
            if new_sig:
                cause = ("first_compile" if not compiled.seen_sigs
                         else "signature_change")
                compiled.seen_sigs.add(sig)
                telemetry.counter(
                    "executor_compiles_total", "block traces/compiles",
                    labels=("program", "place")).labels(
                        program=prog_label, place=place_label).inc()
                telemetry.counter(
                    "executor_compile_seconds_total",
                    "XLA compile wall seconds spent inside Executor.run",
                    labels=("program", "place")).labels(
                        program=prog_label, place=place_label).inc(compile_s)
                telemetry.log_event(
                    "compile", program=prog_label, place=place_label,
                    cause=cause, seconds=compile_s,
                    signature=[list(s) for s in sig])
                if cause == "first_compile" and not internal_run:
                    # static memory analysis once per compiled block: an
                    # extra AOT lower/compile from avals (the persistent
                    # compilation cache absorbs the XLA work); advisory —
                    # a failure must never fail the training step
                    try:
                        memory_mod.on_compile(
                            self, compiled, program, prog_label, place_label,
                            feed_vals, state_vals, np.uint32(rng_counter),
                            signature=sig)
                    except Exception as mem_e:
                        telemetry.log_event(
                            "memory_analysis_error", program=prog_label,
                            error=f"{type(mem_e).__name__}: {mem_e}")
                if cause == "signature_change":
                    last = compiled.last_sig or ()
                    telemetry.counter(
                        "executor_cache_misses_total",
                        "jit retraces caused by a changed feed signature",
                        labels=("program", "place")).labels(
                            program=prog_label, place=place_label).inc()
                    telemetry.log_event(
                        "cache_miss", program=prog_label, place=place_label,
                        signature=[list(s) for s in sig],
                        changed=[list(s) for s in sig if s not in last])
            else:
                telemetry.counter(
                    "executor_cache_hits_total",
                    "runs served by an already-traced signature",
                    labels=("program", "place")).labels(
                        program=prog_label, place=place_label).inc()
            compiled.last_sig = sig
            if check_nan:
                # jit-path equivalent of the reference FLAGS_check_nan_inf
                # per-op scan (executor.cc:325-333): inside one fused XLA
                # computation there is no per-op boundary, so the check runs
                # on every fetch and updated persistable after the step.
                # Probe stat vectors are exempt: their counts describe OTHER
                # tensors (record_probes inspects them below), and a stats
                # l2 that overflowed to inf must not masquerade as a hit.
                # ONE fused on-device reduction + ONE host sync for the
                # whole step (_finite_all); the per-tensor np.asarray walk
                # only runs on the failure path, to name the culprit
                probe_stat_names = ({s.stat_var for s in probe_sites}
                                    if probe_sites else ())
                checked = [
                    (name, val) for name, val in
                    list(zip(fetch_names, fetch_vals)) + list(new_state.items())
                    if name not in probe_stat_names
                    and jnp.issubdtype(getattr(val, "dtype", None)
                                       or np.asarray(val).dtype, jnp.inexact)]
                if checked and not bool(_finite_all([v for _, v in checked])):
                    for name, val in checked:
                        arr = np.asarray(val)
                        if not np.isfinite(arr).all():
                            self._raise_nonfinite(
                                program, name, arr, feed, new_state,
                                rng_counter, scope, prog_label)
        else:
            seed = program.random_seed or 12345
            rng_key = jax.random.fold_in(jax.random.key(seed), rng_counter)
            compile_before = telemetry.jax_compile_seconds()
            run_t0 = time.perf_counter()
            try:
                fetch_vals, fetch_lens, new_state = self._run_eager(
                    program, feed_vals, state_vals, fetch_names, persist_out,
                    rng_key, lod_map, check_nan=check_nan)
            except Exception as e:
                oom = memory_mod.maybe_oom_error(
                    self, program, prog_label, e, feed_vals, state_vals)
                if oom is not None:
                    raise oom from e
                raise
            run_dt = time.perf_counter() - run_t0
            compile_s = telemetry.jax_compile_seconds() - compile_before
            mode, donated, cache_status = "eager", 0, "n/a"
            dyn_stats = None  # dynamics rides the traced step only

        if probe_sites:
            # pop the probe stat vectors (appended after the telemetry
            # extras) and hand them to the inspector BEFORE state writeback:
            # a non-finite probe raises here, so a diverged step never
            # commits its state to the scope
            n_keep = n_user_fetch + len(extra_fetch)
            probe_vals = fetch_vals[n_keep:]
            fetch_vals = fetch_vals[:n_keep]
            fetch_names = fetch_names[:n_keep]
            from . import inspector as inspector_mod
            inspector_mod.record_probes(
                self, program, scope, probe_sites, probe_vals, feed=feed,
                new_state=new_state, rng_counter=rng_counter,
                prog_label=prog_label)

        # the step is now known-good: commit the PRNG counter atomically
        # with (just before) the state write-back below
        scope.set_var("__rng_counter__", rng_counter + 1)
        if dyn_stats is not None:
            dynamics_mod.on_step(program, prog_label, dyn_stats,
                                 int(rng_counter))

        telemetry.counter(
            "executor_runs_total", "Executor.run calls",
            labels=("program", "place", "mode")).labels(
                program=prog_label, place=place_label, mode=mode).inc()
        telemetry.counter(
            "executor_steps_total",
            "training/eval steps executed (a run_steps window counts K)",
            labels=("program", "place")).labels(
                program=prog_label, place=place_label).inc()
        telemetry.histogram(
            "executor_run_seconds",
            "Executor.run wall seconds (dispatch-only unless profiling "
            "forces device sync)", labels=("program", "mode")).labels(
                program=prog_label, mode=mode).observe(run_dt)
        telemetry.gauge(
            "executor_last_step_seconds",
            "wall seconds of the most recent executor step (per-step "
            "average for run_steps windows) — fleet skew input").set(
                max(run_dt - compile_s, 0.0))
        if self._analysis(program)[3]:
            telemetry.counter(
                "optimizer_steps_total",
                "runs of programs carrying optimizer-role ops",
                labels=("program",)).labels(program=prog_label).inc()
        telemetry.log_event(
            "run", program=prog_label, place=place_label, mode=mode,
            seconds=run_dt, compile_s=compile_s,
            execute_s=max(run_dt - compile_s, 0.0), cache=cache_status,
            donated=donated, feeds=len(feed_vals), fetches=n_user_fetch)
        if tracing_mod.enabled():
            t_end = time.monotonic()
            sp = tracing_mod.record_span(
                "step", t_end - run_dt, t_end,
                attrs={"program": prog_label, "place": place_label,
                       "mode": mode, "cache": cache_status})
            if compile_s > 0.0 and cache_status == "miss":
                tracing_mod.record_span(
                    "compile", t_end - run_dt,
                    min(t_end - run_dt + compile_s, t_end), parent=sp,
                    attrs={"seconds": compile_s})

        hbm_sample = None
        if not internal_run:
            # live HBM accounting: one tracker sample per run (gauges +
            # flight-recorder fields below); byte counts come from avals
            # only, so the donated state arrays are safe to measure
            try:
                hbm_sample = memory_mod.on_run(
                    self, program, prog_label, feed_vals, state_vals)
            except Exception:
                hbm_sample = None

        for n, v in new_state.items():
            if n.endswith(SEQLEN_SUFFIX) or n.endswith(SEQLEN2_SUFFIX):
                continue
            if n + SEQLEN_SUFFIX in new_state:
                # sequence state goes back to the scope as a LoDTensor so the
                # next run re-packs it with its lengths intact (incl. the
                # inner lengths of nested lod_level=2 state)
                inner = new_state.get(n + SEQLEN2_SUFFIX)
                packed, lod = padded_to_pack(
                    np.asarray(v), np.asarray(new_state[n + SEQLEN_SUFFIX]),
                    None if inner is None else np.asarray(inner))
                scope.set_var(n, LoDTensor(packed, lod))
            else:
                scope.set_var(n, v)
        if extra_fetch:
            # pop the telemetry side-fetches (gauges, not user outputs);
            # float() forces the device read — the documented cost of
            # _telemetry_fetch_extra (PADDLE_TPU_TELEMETRY_FETCH=0 disables)
            for (metric, _n), val in zip(extra_fetch,
                                         fetch_vals[n_user_fetch:]):
                try:
                    telemetry.gauge(metric, labels=("program",)).labels(
                        program=prog_label).set(
                            float(np.asarray(val).ravel()[0]))
                except (TypeError, ValueError, IndexError):
                    pass
            fetch_vals = fetch_vals[:n_user_fetch]
            fetch_names = fetch_names[:n_user_fetch]
        if not internal_run:
            from . import inspector as inspector_mod
            if inspector_mod.flight_enabled():
                # flight recorder: one bounded ring record per step (after
                # the gauge pop above so the global norm is this step's)
                inspector_mod.record_step(program, prog_label, {
                    "place": place_label, "mode": mode, "seconds": run_dt,
                    "compile_s": compile_s, "cache": cache_status,
                    "feeds": len(feed_vals), "fetches": n_user_fetch,
                    "rng_counter": int(rng_counter),
                    "global_norm": telemetry.read_gauge(
                        "optimizer_global_norm", program=prog_label),
                    "hbm_bytes_in_use": (hbm_sample or {}).get(
                        "bytes_in_use"),
                    "hbm_peak_bytes": (hbm_sample or {}).get("peak_bytes"),
                })
        # Fetched sequence vars come back in the reference's packed layout
        # ([sum_len, ...] rows): numpy mode returns the packed array, LoDTensor
        # mode additionally carries the offsets.
        rebuilt = []
        for n, v in zip(fetch_names, fetch_vals):
            lens = fetch_lens.get(n)
            inner = fetch_lens.get(n + SEQLEN2_SUFFIX)
            if lens is None and not return_numpy:
                # keep the fetch on-device: np.asarray would force a
                # device->host sync per step, which return_numpy=False
                # callers (benchmarks, pipelined training loops) avoid
                rebuilt.append(v)
                continue
            arr = np.asarray(v)
            if lens is not None:
                lens = np.asarray(lens)
                # ignore spuriously-tagged non-sequence fetches
                if arr.ndim < 2 or lens.shape[0] != arr.shape[0] or \
                        (lens.size and lens.max() > arr.shape[1]):
                    lens = None
            if inner is not None and lens is not None:
                inner = np.asarray(inner)
                if arr.ndim < 3 or inner.shape[:2] != arr.shape[:2] or \
                        (inner.size and inner.max() > arr.shape[2]):
                    inner = None
            if lens is not None:
                packed, lod = padded_to_pack(arr, lens, inner)
                rebuilt.append(np.asarray(packed) if return_numpy
                               else LoDTensor(packed, lod))
            else:
                rebuilt.append(arr if return_numpy else v)
        return rebuilt

    def _raise_nonfinite(self, program, name, arr, feed, new_state,
                         rng_counter, scope, prog_label):
        """Structured error for a fetch-level check_nan_inf hit: names the
        offending fetch var and dtype, counts the contamination, and (when
        the nonfinite_attribution flag is on) replays the step with
        bisection probes to name the first offending op."""
        from . import inspector as inspector_mod
        from .errors import NonFiniteError
        telemetry.counter(
            "nonfinite_detections_total",
            "NaN/Inf values caught by check_nan_inf or inspector probes",
            labels=("program", "source")).labels(
                program=prog_label, source="fetch").inc()
        nan_c = int(np.isnan(arr).sum())
        inf_c = int(np.isinf(arr).sum())
        msg = (f"NaN/Inf detected in variable '{name}' (dtype {arr.dtype}, "
               f"shape {tuple(arr.shape)}, {nan_c} NaN / {inf_c} Inf) "
               f"after jitted step (check_nan_inf)")
        attribution = None
        if flags_mod.get("nonfinite_attribution"):
            try:
                attribution = inspector_mod.attribute_nonfinite(
                    self, program, feed, scope=scope, state=new_state,
                    rng_counter=rng_counter)
            except Exception:
                attribution = None
            if attribution is not None:
                msg += "\n  " + attribution.summary()
        raise NonFiniteError(msg, var_name=name, dtype=str(arr.dtype),
                             attribution=attribution,
                             feed_signature=inspector_mod.feed_signature(
                                 feed))

    def close(self):
        self._cache.clear()
        self._analysis_cache.clear()

    # --- analysis -----------------------------------------------------------
    @staticmethod
    def _block_reads_writes(program, block, reads, writes, produced):
        for op in block.ops:
            if op.type in ("feed", "fetch"):
                continue
            for name in op.input_arg_names:
                if name not in produced:
                    reads.add(name)
            for a in op.desc.attrs.values():
                from .framework.desc import BlockRef, BlocksRef
                sub_idxs = []
                if isinstance(a, BlockRef):
                    sub_idxs = [a.idx]
                elif isinstance(a, BlocksRef):
                    sub_idxs = a.idxs
                for si in sub_idxs:
                    Executor._block_reads_writes(
                        program, program.block(si), reads, writes, set(produced))
            for name in op.output_arg_names:
                produced.add(name)
                writes.add(name)

    def _analysis(self, program):
        """Per-(program, version) cached read/write sets + persistable map +
        whether the block carries optimizer-role ops (telemetry's run-time
        train-step counter). The full block walk costs milliseconds on a
        ResNet-scale program and used to run twice per Executor.run — at
        TPU step rates that was a measurable host-side stall between
        steps."""
        key = (id(program), getattr(program, "_version", 0))
        hit = self._analysis_cache.get(key)
        if hit is not None and hit[0] is program:
            return hit[1], hit[2], hit[3], hit[4]
        reads, writes = set(), set()
        self._block_reads_writes(program, program.global_block(),
                                 reads, writes, set())
        persistable = {}
        for b in program.blocks:
            for name, v in b.desc.vars.items():
                if v.persistable:
                    persistable[name] = True
        has_optimize = any(
            op.desc.attrs.get("op_role") == "optimize"
            for op in program.global_block().ops)
        # keep a strong program ref: the cache key uses id(program)
        self._analysis_cache[key] = (program, reads, writes, persistable,
                                     has_optimize)
        return reads, writes, persistable, has_optimize

    def _external_inputs(self, program, fed: set, scope) -> List[str]:
        """Vars the block reads from the scope: already-present scope vars or
        declared persistables. Reads of undeclared/absent vars are optional
        inputs (grad cotangents never produced) and resolve to None.
        (Computing reads with an empty produced-set and subtracting `fed`
        is equivalent to seeding produced with `fed`: a fed var read before
        production lands in reads and is then subtracted.)"""
        reads, _writes, persistable, _ = self._analysis(program)
        out = []
        for n in sorted(reads - fed):
            if scope.has_var(n) and scope.find_var(n) is not None:
                out.append(n)
            elif persistable.get(n):
                out.append(n)
        return out

    def _persistable_outputs(self, program) -> List[str]:
        _reads, writes, persistable, _ = self._analysis(program)
        return [n for n in sorted(writes) if persistable.get(n)]

    # --- execution ----------------------------------------------------------
    def _exec_op(self, ctx: LoweringContext, op, env: Dict[str, Any]):
        if op.type in ("feed", "fetch"):
            return
        _record_op(op.type)
        try:
            opdef = registry.get(op.type)
        except KeyError as e:
            raise RuntimeError(
                f"Operator '{op.type}' is not registered "
                f"(outputs {op.output_arg_names}); available ops: "
                f"{len(registry.registered_ops())} registered") from e
        if opdef.lower is None:
            raise RuntimeError(
                f"Operator '{op.type}' has no kernel lowering "
                f"(inputs {dict(op.desc.inputs)}, "
                f"outputs {dict(op.desc.outputs)})")
        prev_env = ctx.env
        ctx.env = env
        ctx.seq_overrides = {}
        ctx.layout_overrides = {}
        propagate_tag = None
        if ctx.layout_opt:
            from .ops import layout as layout_mod
            propagate_tag = layout_mod.prepass(ctx.layouts, op, op.type, env)
        ins = {slot: [env.get(n) for n in names]
               for slot, names in op.desc.inputs.items()}
        if op.type not in _SPARSE_AWARE_OPS:
            # SelectedRows grads (sparse embedding path) densify at the
            # boundary of any op without a sparse kernel — the analogue of
            # the reference's per-kernel SelectedRows dispatch. Counted:
            # this is the invisible perf cliff sparse_densify_fallback_total
            # exists to surface (a clip/regularizer/cast in the grad chain
            # silently turns O(rows) into O(table)).
            from .ops.common import SelectedRowsVal
            newins = {}
            hit = False
            for slot, vals in ins.items():
                conv = []
                for v in vals:
                    if isinstance(v, SelectedRowsVal):
                        hit = True
                        v = v.to_dense()
                    conv.append(v)
                newins[slot] = conv
            if hit:
                sparse_ops_mod.count_densify(op.type, "sparse_unaware_op")
            ins = newins
        t0 = time.perf_counter() if _BENCHMARK and _EAGER else None
        try:
            # the scope lands in every emitted HLO instruction's
            # metadata op_name ("jit(fn)/.../pd.<type>/<prim>") — the hook
            # the profiler's per-op device table joins timings against
            # (profiler._print_device_table / xplane.hlo_op_names)
            with jax.named_scope(f"pd.{op.type}"):
                outs = opdef.lower(ctx, op, ins)
        except (AssertionError, TypeError, ValueError, IndexError) as e:
            # PADDLE_ENFORCE-style context (reference platform/enforce.h +
            # utils/CustomStackTrace.h layer-stack dump): name the failing
            # operator, its variables, the live input shapes, and the user
            # line that built the op, instead of a bare JAX traceback
            from .errors import EnforceNotMet
            shapes = {slot: [getattr(v, "shape", None) for v in vals]
                      for slot, vals in ins.items()}
            site = getattr(op, "creation_site", None)
            raise EnforceNotMet(
                f"Operator {op.type} failed: {e}\n"
                f"  inputs: {dict(op.desc.inputs)}\n"
                f"  input shapes: {shapes}\n"
                f"  outputs: {dict(op.desc.outputs)}\n"
                f"  built at: {site or '<unknown>'}",
                op_type=op.type, creation_site=site) from e
        if _op_observers:
            for obs in _op_observers:
                obs(op, ins, outs)
        if t0 is not None:
            # FLAGS_benchmark parity (reference executor.cc:321): wait for
            # device completion per op and log wall time
            jax.block_until_ready(jax.tree.leaves(
                {k: [v for v in vs if v is not None]
                 for k, vs in outs.items()}))
            vlog(1, f"[benchmark] {op.type}: "
                    f"{(time.perf_counter() - t0) * 1e3:.3f} ms")
        # Default SEQLEN propagation mirrors the reference's LoD propagation
        # (most ops share LoD with their first sequence input); sequence
        # lowerings override via ctx.set_seq_len. Inheritance is restricted
        # to outputs that PRESERVE the carrier's [batch, time] leading dims —
        # an op that drops or reshapes the time axis (reductions, matmul
        # collapses) is no longer a sequence, and tagging it would make the
        # fetch path spuriously repack a dense tensor.
        inherited = None
        inherited2 = None
        carrier_shape = None
        for names in op.desc.inputs.values():
            for n in names:
                if n + SEQLEN_SUFFIX in env:
                    inherited = env[n + SEQLEN_SUFFIX]
                    inherited2 = env.get(n + SEQLEN2_SUFFIX)
                    carrier_shape = getattr(env.get(n), "shape", None)
                    break
            if inherited is not None:
                break
        for slot, names in op.desc.outputs.items():
            vals = outs.get(slot, [])
            for name, val in zip(names, vals):
                if val is not None:
                    env[name] = val
                    if name + SEQLEN2_SUFFIX in ctx.seq_overrides:
                        sl2 = ctx.seq_overrides[name + SEQLEN2_SUFFIX]
                        if sl2 is None:
                            env.pop(name + SEQLEN2_SUFFIX, None)
                        else:
                            env[name + SEQLEN2_SUFFIX] = sl2
                    if name in ctx.seq_overrides:
                        sl = ctx.seq_overrides[name]
                        if sl is None:
                            env.pop(name + SEQLEN_SUFFIX, None)
                        else:
                            env[name + SEQLEN_SUFFIX] = sl
                    elif inherited is not None and hasattr(val, "ndim") \
                            and getattr(val, "ndim", 0) >= 2 \
                            and carrier_shape is not None \
                            and len(carrier_shape) >= 2 \
                            and tuple(val.shape[:2]) == tuple(carrier_shape[:2]):
                        env[name + SEQLEN_SUFFIX] = inherited
                        if inherited2 is not None and \
                                name + SEQLEN2_SUFFIX not in ctx.seq_overrides:
                            env[name + SEQLEN2_SUFFIX] = inherited2
        if ctx.layout_opt and (ctx.layouts or propagate_tag
                               or ctx.layout_overrides):
            from .ops import layout as layout_mod
            layout_mod.tag_outputs(ctx.layouts, op, env, propagate_tag,
                                   ctx.layout_overrides)
        ctx.env = prev_env

    def _trace_block(self, program, feed_vals, state_vals, fetch_names,
                     persist_out, rng_key, lod_map, grab_names=()):
        env: Dict[str, Any] = {}
        env.update(state_vals)
        env.update(feed_vals)
        ctx = LoweringContext(self, program, rng_key, lod_map)
        block = program.global_block()
        # trace-time fusion pass (ops/fusion.py, PADDLE_TPU_FUSION=1):
        # planned windows lower as one fused op at their anchor index;
        # everything else keeps the per-op path. The plan is fetch-
        # agnostic, so fold-mode elision re-checks against the names this
        # trace must materialize.
        from .ops import fusion as fusion_mod
        from .parallel import overlap as overlap_mod
        groups = fusion_mod.plan(program)
        # communication/compute overlap pass (parallel/overlap.py,
        # PADDLE_TPU_OVERLAP=1): dp gradient buckets flush — pin to the
        # replicated sharding under their pd.coll scope — right after
        # their last producing grad op, instead of resolving lazily at
        # the optimizer. Bitwise-neutral; only the sync point moves.
        oplan = overlap_mod.plan(program)
        if not groups and oplan is None:
            for op in block.ops:
                self._exec_op(ctx, op, env)
        else:
            protected = set(fetch_names) | set(persist_out)
            ops = block.ops
            groups = groups or {}
            i = 0
            while i < len(ops):
                g = groups.get(i)
                if g is not None:
                    fusion_mod.execute_group(self, ctx, g, env, protected)
                    nxt = g.end
                else:
                    self._exec_op(ctx, ops[i], env)
                    nxt = i + 1
                if oplan is not None:
                    # anchors inside a fused window flush after the window
                    oplan.flush_range(ctx, env, i, nxt)
                i = nxt
        if ctx.layouts:
            # fetches and persistable state leave the trace in canonical
            # NCHW — the internal NHWC convention never escapes a run
            from .ops import layout as layout_mod
            layout_mod.canonicalize(ctx.layouts, env,
                                    list(fetch_names) + list(persist_out))
        from .ops.common import maybe_dense
        fetch = [maybe_dense(env[n], count_as="fetch") for n in fetch_names]
        # lengths side channel for fetched sequence vars, so run() can
        # rebuild LoDTensors (padded_to_pack) when return_numpy=False
        fetch_lens = {n: env[n + SEQLEN_SUFFIX] for n in fetch_names
                      if n + SEQLEN_SUFFIX in env}
        for n in fetch_names:
            if n + SEQLEN2_SUFFIX in env:
                fetch_lens[n + SEQLEN2_SUFFIX] = env[n + SEQLEN2_SUFFIX]
        new_state = {n: env[n] for n in persist_out if n in env}
        # state read but never written flows through unchanged
        for n in state_vals:
            if n not in new_state and not n.endswith(SEQLEN_SUFFIX):
                for b in program.blocks:
                    if b.desc.has_var(n) and b.desc.var(n).persistable:
                        new_state[n] = env[n]
                        break
        # lengths side channels for sequence-state write-back — only for vars
        # *declared* as sequences (lod_level>0): the default SEQLEN
        # propagation in _exec_op can spuriously tag non-sequence outputs
        # (e.g. a parameter updated from a sequence-derived gradient)
        for n in list(new_state):
            if n + SEQLEN_SUFFIX not in env:
                continue
            for b in program.blocks:
                if b.desc.has_var(n):
                    if b.desc.var(n).lod_level > 0:
                        new_state[n + SEQLEN_SUFFIX] = env[n + SEQLEN_SUFFIX]
                        if n + SEQLEN2_SUFFIX in env and \
                                b.desc.var(n).lod_level > 1:
                            new_state[n + SEQLEN2_SUFFIX] = \
                                env[n + SEQLEN2_SUFFIX]
                    break
        # raw trace values the dynamics reduction reads (grad vars): no
        # maybe_dense — SelectedRows grads reduce sparse — and no layout
        # canonicalize, the stats are layout-invariant reductions
        grabs = {n: env[n] for n in grab_names if n in env}
        return fetch, fetch_lens, new_state, grabs

    def _make_step_fn(self, program, fetch_names, persist_out, lod_map):
        """The pure per-step function `fn(feed_vals, state_vals, rng_counter)
        -> (fetch, lens, new_state)` both compile paths share: _compile jits
        it directly; _compile_window wraps it in a lax.scan over a stacked
        feed window."""
        mesh = getattr(program, "_mesh", None)
        param_specs = getattr(program, "_param_shardings", {})
        seed = program.random_seed or 12345
        dyn_plan = dynamics_mod.plan(program)

        def _state_spec(n):
            # accumulators of ANY sharded parameter inherit its sharding
            # (parallel/embedding.resolve_state_spec, generalized past
            # tables by the planner) so adam moments of a 1M-row table —
            # or an fsdp-sharded fc weight — never replicate per device
            spec = param_specs.get(n)
            if spec is None and (param_specs or
                                 getattr(program, "_sharded_tables", None)):
                from .parallel import embedding as embedding_mod
                spec = embedding_mod.resolve_state_spec(program, n)
            return spec

        def fn(feed_vals, state_vals, rng_counter):
            # key derivation INSIDE the jit: the per-step fold_in costs
            # nothing host-side (eagerly it was ~3ms/step of tiny
            # dispatches, measurable against a ~100ms ResNet step)
            rng_key = jax.random.fold_in(jax.random.key(seed), rng_counter)
            fetch, lens, new_state, grabs = self._trace_block(
                program, feed_vals, state_vals, fetch_names, persist_out,
                rng_key, lod_map,
                grab_names=dyn_plan.grab_names if dyn_plan else ())
            # fused dynamics reduction over pre-pin values (the stats are
            # scalars; pinning them replicated below would be a no-op
            # anyway, but the weights/grads must be the trace's own)
            dyn_stats = dynamics_mod.sampled_stats(
                dyn_plan, state_vals, new_state, grabs, rng_counter)
            if mesh is not None:
                # pin state outputs to the same shardings the next run's
                # in_shardings expect (annotated params keep their spec,
                # everything else replicated) — otherwise XLA may choose a
                # sharded layout for an output and the donated round-trip
                # mismatches on the following step
                from jax.sharding import NamedSharding, PartitionSpec
                from .parallel._collectives import coll_scope
                pinned = {}
                for n, v in new_state.items():
                    spec = _state_spec(n)
                    sh = NamedSharding(mesh, PartitionSpec(*spec)) if spec \
                        else NamedSharding(mesh, PartitionSpec())
                    try:
                        if spec:
                            # annotated (tensor/ZeRO-sharded) params: the
                            # resharding collectives GSPMD inserts here get
                            # a pd.coll site so fleet.py attributes them;
                            # replicated pins stay untagged (usually no-ops)
                            with coll_scope("tp_state_pin"):
                                pinned[n] = \
                                    jax.lax.with_sharding_constraint(v, sh)
                        else:
                            pinned[n] = \
                                jax.lax.with_sharding_constraint(v, sh)
                    except (TypeError, ValueError):
                        pinned[n] = v
                new_state = pinned
            if dyn_stats is not None:
                # rides new_state through the donated round-trip; the
                # executor pops it before check_nan and scope writeback
                new_state[dynamics_mod.STATE_KEY] = dyn_stats
            return fetch, lens, new_state

        return fn

    def _shardings(self, program, state_names, feed_names, *, window=False):
        """SPMD in_shardings for the compiled step, or None off-mesh: feeds
        sharded along batch over the 'dp' axis, state (parameters /
        accumulators) replicated unless annotated. XLA GSPMD inserts the
        gradient AllReduce over ICI — the TPU-native replacement for the
        reference's pserver/NCCL paths (SURVEY.md §2.5). With window=True
        each feed gains a leading steps axis, so its per-step spec shifts
        right by one (the scan axis is never sharded)."""
        mesh = getattr(program, "_mesh", None)
        if mesh is None:
            return None
        param_specs = getattr(program, "_param_shardings", {})
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(mesh, PartitionSpec())

        # per-parameter PartitionSpec annotations (tensor / ZeRO
        # sharding, parallel/tensor_parallel.py); sharded-table optimizer
        # accumulators inherit their table's row sharding
        # (parallel/embedding.resolve_state_spec); everything else is
        # replicated and XLA GSPMD partitions the consumers
        state_shardings = {}
        has_specs = bool(param_specs) or \
            bool(getattr(program, "_sharded_tables", None))
        if has_specs:
            from .parallel import embedding as embedding_mod
        for n in state_names:
            spec = param_specs.get(n)
            if spec is None and has_specs:
                spec = embedding_mod.resolve_state_spec(program, n)
            state_shardings[n] = repl if spec is None else \
                NamedSharding(mesh, PartitionSpec(*spec))

        # Feed sharding rule: an explicit per-feed override
        # (program._feed_shardings[name] = spec tuple, see
        # parallel.shard_feed) wins; otherwise feeds batch-shard on
        # the axis named 'dp' when the mesh has one, and replicate on
        # meshes without a data axis (sp/ep/mp-only meshes must opt
        # in via shard_feed). @SEQLEN sidecars are [batch] vectors
        # and follow their base feed's batch (dim-0) axis.
        feed_specs = getattr(program, "_feed_shardings", {})
        dp_axis = "dp" if "dp" in mesh.axis_names else None
        default_spec = (dp_axis,) if dp_axis else ()

        def _feed_spec(n):
            if n.endswith(SEQLEN2_SUFFIX):
                base = n[: -len(SEQLEN2_SUFFIX)]
            elif n.endswith(SEQLEN_SUFFIX):
                base = n[: -len(SEQLEN_SUFFIX)]
            else:
                base = None
            if base is not None:
                bspec = feed_specs.get(base)
                if bspec is not None:
                    return (bspec[0] if bspec else None,)
                return default_spec
            spec = feed_specs.get(n)
            if spec is not None:
                return tuple(spec)
            return default_spec

        def _feed_sharding(n):
            spec = _feed_spec(n)
            if window:
                spec = (None,) + spec
            return NamedSharding(mesh, PartitionSpec(*spec))

        feed_shardings = {n: _feed_sharding(n) for n in feed_names}
        return feed_shardings, state_shardings, repl

    def _jit_compile(self, program, fn, sh):
        """The ONE jax.jit call site for both compile paths (per-step and
        the run_steps scan window). Consolidated so compiler options — the
        overlap pass's async-collective + latency-hiding-scheduler set
        today, anything else tomorrow — reach EVERY path; before this the
        four duplicated call sites each had to be patched in step.
        tools/check_registry.py lints this file down to exactly one
        direct jit call site, so a new path can't silently skip it.
        `compiler_options()` returns None (plain compile) off-mesh, off-
        gate, on non-TPU backends, or when the probe rejects the set.

        State is donated only off-CPU: XLA CPU never aliases donated
        buffers (the donation audit's alias=0B warning), so donation buys
        nothing there — and it is actively unsafe when a state entry is a
        scope-held numpy array, because CPU device_put may zero-copy an
        aligned host buffer and donating memory jax does not own corrupts
        the heap (flaky SIGSEGV/garbage reads, alignment- and therefore
        allocation-order-dependent)."""
        from .parallel import overlap as overlap_mod
        plat = getattr(self.device, "platform", None) or jax.default_backend()
        kwargs: Dict[str, Any] = {}
        if plat != "cpu":
            kwargs["donate_argnums"] = (1,)
        if sh is not None:
            feed_shardings, state_shardings, repl = sh
            kwargs["in_shardings"] = (feed_shardings, state_shardings, repl)
        opts = overlap_mod.compiler_options(program)
        if opts:
            kwargs["compiler_options"] = opts
        return jax.jit(fn, **kwargs)

    def _maybe_verify(self, program, feed_names, fetch_names):
        """PADDLE_TPU_VERIFY=1: run the static analyzer once per program
        version on the cache-miss path (so a verified program costs
        nothing on later steps) and refuse to trace a program with
        error-severity diagnostics."""
        if not _VERIFY:
            return
        key = (id(program), getattr(program, "_version", 0))
        seen = getattr(self, "_verified_programs", None)
        if seen is None:
            seen = self._verified_programs = set()
        if key in seen:
            return
        from .analysis import analyze_program
        report = analyze_program(program, feeds=list(feed_names),
                                 fetches=list(fetch_names))
        if report.errors:
            from .errors import ProgramVerifyError
            raise ProgramVerifyError(
                report.errors, program_name=getattr(program, "name", None))
        seen.add(key)

    def _compile(self, program, state_names, feed_names, fetch_names,
                 persist_out, lod_map) -> _CompiledBlock:
        self._maybe_verify(program, feed_names, fetch_names)
        fn = self._make_step_fn(program, fetch_names, persist_out, lod_map)
        sh = self._shardings(program, state_names, feed_names)
        jitted = self._jit_compile(program, fn, sh)
        return _CompiledBlock(jitted, state_names, feed_names, fetch_names,
                              program)

    def prepare_serving(self, program, feed_names, fetch_names, scope):
        """Compile one inference program for the serving engine and return
        (compiled_block, state_names, persist_out). This is the stable
        seam between serving/ and the executor: the engine AOT-lowers
        per-bucket executables from compiled_block.fn (jit's .lower() on
        explicit avals) instead of re-implementing tracing, sharding
        resolution, or the donation contract. Raises the same
        missing-state error as Executor.run when a persistable the block
        reads has no value in `scope` (startup never ran / load_persistables
        skipped a file)."""
        feed_names = sorted(feed_names)
        state_names = self._external_inputs(program, set(feed_names), scope)
        persist_out = self._persistable_outputs(program)
        missing = [n for n in state_names if scope.find_var(n) is None]
        if missing:
            raise RuntimeError(
                f"Variables {missing} are read by the program but absent "
                f"from the scope — run the startup program first.")
        compiled = self._compile(program, state_names, feed_names,
                                 fetch_names, persist_out, lod_map={})
        return compiled, state_names, persist_out

    def _compile_window(self, program, state_names, feed_names, fetch_names,
                        persist_out, lod_map, steps, fetch_mode) \
            -> _CompiledBlock:
        """Compile a K-step fused window: the per-step fn wrapped in a
        jax.lax.scan whose carry is (persistable state, rng counter) and
        whose xs is a feed dict with a leading [K] axis. One Python
        dispatch, one donation, one write-back per K steps; per-step rng
        parity comes from carrying the same uint32 counter the per-step
        path folds in (step i of the window uses counter+i, bitwise what K
        sequential runs would use)."""
        self._maybe_verify(program, feed_names, fetch_names)
        step_fn = self._make_step_fn(program, fetch_names, persist_out,
                                     lod_map)

        def fnK(window_feed, state_vals, rng_counter):
            def body(carry, feed_slice):
                state, counter = carry
                fetch, lens, new_state = step_fn(feed_slice, state, counter)
                if lens:
                    raise _WindowUnsupported(
                        f"sequence fetches {sorted(lens)} need per-batch "
                        f"LoD reconstruction")
                # persistables written by the step ride the carry; state
                # that is read but never written flows through unchanged;
                # written-but-never-read persistables (no feedback edge)
                # leave as per-step outputs and the last slice wins —
                # exactly K sequential runs' write-back order
                carry_state = {n: new_state.get(n, state[n]) for n in state}
                extras = {n: v for n, v in new_state.items()
                          if n not in state}
                return (carry_state, counter + jnp.uint32(1)), (fetch, extras)

            init = (state_vals, jnp.uint32(rng_counter))
            (final_state, _), (fetch_seq, extra_seq) = jax.lax.scan(
                body, init, window_feed)
            if fetch_mode == "stack":
                fetch = list(fetch_seq)
            elif fetch_mode == "mean":
                fetch = [jnp.mean(f, axis=0) for f in fetch_seq]
            else:  # "last"
                fetch = [f[-1] for f in fetch_seq]
            new_state = dict(final_state)
            for n, v in extra_seq.items():
                # the dynamics stats row keeps its full [K, ...] stack —
                # the observatory picks the period-boundary slices out
                new_state[n] = v if n == dynamics_mod.STATE_KEY else v[-1]
            return fetch, new_state

        sh = self._shardings(program, state_names, feed_names, window=True)
        jitted = self._jit_compile(program, fnK, sh)
        return _CompiledBlock(jitted, state_names, feed_names, fetch_names,
                              program)

    def _run_eager(self, program, feed_vals, state_vals, fetch_names,
                   persist_out, rng_key, lod_map, check_nan=False):
        env: Dict[str, Any] = {}
        env.update({k: jnp.asarray(v) for k, v in state_vals.items()})
        env.update({k: jnp.asarray(v) for k, v in feed_vals.items()})
        ctx = LoweringContext(self, program, rng_key, lod_map)
        block = program.global_block()
        from . import profiler as profiler_mod
        for op in block.ops:
            # per-op host events in the interpreter path (reference
            # RecordEvent around each kernel launch, operator.cc:486)
            with profiler_mod.record(op.type):
                self._exec_op(ctx, op, env)
            if check_nan and op.type != "tensor_stats":
                # per-op scan (reference executor.cc:325 FLAGS_check_nan_inf
                # semantics); in eager mode the op boundary IS available, so
                # the error names the producing op directly — no bisection
                # replay needed. tensor_stats outputs are exempt for the
                # same reason as in the jit path.
                for name in op.output_arg_names:
                    v = env.get(name)
                    if v is not None and jnp.issubdtype(
                            jnp.asarray(v).dtype, jnp.inexact):
                        if not bool(jnp.all(jnp.isfinite(v))):
                            from .errors import NonFiniteError
                            raise NonFiniteError(
                                f"NaN/Inf in output '{name}' of op "
                                f"{op.type}",
                                var_name=name, op_type=op.type,
                                dtype=str(jnp.asarray(v).dtype))
        if ctx.layouts:
            from .ops import layout as layout_mod
            layout_mod.canonicalize(ctx.layouts, env,
                                    list(fetch_names) + list(persist_out)
                                    + list(state_vals))
        from .ops.common import maybe_dense
        fetch = [maybe_dense(env[n], count_as="fetch") for n in fetch_names]
        fetch_lens = {n: env[n + SEQLEN_SUFFIX] for n in fetch_names
                      if n + SEQLEN_SUFFIX in env}
        for n in fetch_names:
            if n + SEQLEN2_SUFFIX in env:
                fetch_lens[n + SEQLEN2_SUFFIX] = env[n + SEQLEN2_SUFFIX]
        new_state = {}
        for n in set(persist_out) | set(state_vals):
            if n.endswith(SEQLEN_SUFFIX):
                continue
            if n in env:
                for b in program.blocks:
                    if b.desc.has_var(n) and b.desc.var(n).persistable:
                        new_state[n] = env[n]
                        break
        for n in list(new_state):
            if n + SEQLEN_SUFFIX not in env:
                continue
            for b in program.blocks:
                if b.desc.has_var(n):
                    if b.desc.var(n).lod_level > 0:
                        new_state[n + SEQLEN_SUFFIX] = env[n + SEQLEN_SUFFIX]
                        if n + SEQLEN2_SUFFIX in env and \
                                b.desc.var(n).lod_level > 1:
                            new_state[n + SEQLEN2_SUFFIX] = \
                                env[n + SEQLEN2_SUFFIX]
                    break
        return fetch, fetch_lens, new_state
