"""RecordIO reader/writer over the native C++ library
(reference: paddle/fluid/recordio/ + python/paddle/fluid/recordio_writer.py).

The chunked/CRC'd/optionally-compressed format lives in C++
(native/recordio.cc, built lazily with g++ into librecordio.so); this module
binds it via ctypes and layers the sample-serialization used by readers:
each record is a pickled tuple of numpy arrays."""

from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
from typing import Iterable, Optional

__all__ = ["RecordIOWriter", "RecordIOScanner", "write_samples",
           "read_samples", "convert_reader_to_recordio_file"]

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "librecordio.so")
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) <
            os.path.getmtime(os.path.join(_NATIVE_DIR, "recordio.cc"))):
        subprocess.run(["make", "-s", "-C", _NATIVE_DIR],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.recordio_writer_open.restype = ctypes.c_void_p
    lib.recordio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                         ctypes.c_int]
    lib.recordio_writer_write.restype = ctypes.c_int
    lib.recordio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int]
    lib.recordio_writer_close.restype = ctypes.c_int
    lib.recordio_writer_close.argtypes = [ctypes.c_void_p]
    lib.recordio_scanner_open.restype = ctypes.c_void_p
    lib.recordio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.recordio_scanner_next.restype = ctypes.POINTER(ctypes.c_char)
    lib.recordio_scanner_next.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_int)]
    lib.recordio_scanner_error.restype = ctypes.c_int
    lib.recordio_scanner_error.argtypes = [ctypes.c_void_p]
    lib.recordio_scanner_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class RecordIOWriter:
    def __init__(self, path: str, compressor: str = "snappy",
                 max_num_records: int = 1000):
        lib = _load()
        # the reference offers snappy; zlib is this build's compressor and
        # "snappy" maps onto it (capability parity: compressed chunks)
        comp = 0 if compressor in (None, "none", "no") else 1
        self._h = lib.recordio_writer_open(path.encode(), comp, 1 << 20)
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, data: bytes):
        rc = _load().recordio_writer_write(self._h, data, len(data))
        if rc != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            rc = _load().recordio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio close/flush failed")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordIOScanner:
    def __init__(self, path: str):
        self._h = _load().recordio_scanner_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def __iter__(self):
        lib = _load()
        n = ctypes.c_int(0)
        while True:
            p = lib.recordio_scanner_next(self._h, ctypes.byref(n))
            if not p:
                # distinguish clean EOF from mid-file corruption: the
                # reference raises on a bad chunk rather than silently
                # yielding a truncated dataset
                if lib.recordio_scanner_error(self._h):
                    raise IOError(
                        "recordio stream ended on a corrupted chunk "
                        "(CRC mismatch, bad magic, or truncated file)")
                break
            yield ctypes.string_at(p, n.value)

    def close(self):
        if self._h:
            _load().recordio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def write_samples(path: str, samples: Iterable, compressor="snappy"):
    with RecordIOWriter(path, compressor) as w:
        for s in samples:
            w.write(pickle.dumps(s, protocol=pickle.HIGHEST_PROTOCOL))


def read_samples(path: str):
    with RecordIOScanner(path) as s:
        for rec in s:
            yield pickle.loads(rec)


def convert_reader_to_recordio_file(filename, reader_creator,
                                    compressor="snappy",
                                    feeder=None):
    """Reference recordio_writer.py API: dump a reader's samples to a file."""
    write_samples(filename, reader_creator(), compressor)
    return filename
