"""Fleet observability: per-collective bandwidth attribution, cross-host
straggler detection, and goodput accounting (ISSUE 8 tentpole).

Extends the single-host observability stack (telemetry / memory /
roofline) across the mesh and the fleet, three layers:

1. **Per-collective attribution** — xplane device events classified into
   collective kinds (xplane.COLLECTIVE_KINDS) and joined to framework
   call sites through `pd.coll.<site>` named scopes
   (parallel/_collectives.coll_scope) landing in HLO metadata op_name.
   Each (kind, site) row carries bytes moved (HLO output shapes), device
   time, the exposed-vs-overlapped split (xplane.exposed_in_line), and
   achieved bus bandwidth with the nccl-tests algbw→busbw factors —
   judged against the measured ICI/DCN link roofline
   (roofline.ensure_ici, PADDLE_TPU_ICI_GBPS override) as `% of link`.

2. **Cross-host skew** — a FleetSnapshot per host (step time, device
   duty cycle, infeed wait, collective wait, hbm gauges) allgathered
   over the jax.distributed coordination service
   (multihost.allgather_bytes — control plane, works on the CPU
   backend), reduced into max/median step-time skew and a slowest-host
   attribution (compute vs infeed vs collective-wait), published as the
   `fleet_step_skew` / `fleet_straggler_host` gauges.

3. **Goodput accounting** — the run ledger: wall span split into
   productive step time vs badput buckets (compile, checkpoint save,
   restore, input stall, collective wait, idle) from telemetry events
   already emitted by the executor, io.py and multihost checkpointing.
   Published as `goodput_fraction` + `goodput_seconds{bucket}`.

Consumers: `python -m paddle_tpu fleet` (CLI), `perf`'s report
(roofline.collect_report embeds `collectives`), profiler.stop_profiler
(fleet summary line when multi-process) and the bench harnesses
(`busbw`, `fleet_skew`, `goodput` JSON fields).
"""

from __future__ import annotations

import json
import shutil
import tempfile
from typing import Any, Dict, List, Optional

__all__ = ["collective_table", "busbw_by_kind", "exposed_summary",
           "local_snapshot", "fleet_snapshot", "goodput_report",
           "format_goodput", "format_fleet", "capture"]

UNATTRIBUTED = "(unattributed)"


# --- per-collective bandwidth attribution -----------------------------------

def collective_table(trace_dir, hlo_texts=(), steps: Optional[int] = None,
                     probe: bool = True) -> Dict[str, Any]:
    """Join the trace's collective device events to the compiled modules'
    collective instructions into per-(kind, site) rows:

        {"rows": [{kind, site, count, bytes, time_ms, exposed_ms,
                   algbw_gbps, busbw_gbps, pct_link, overlap_frac}],
         "ici_gbps": float|None, "participants": int|None}

    `bytes` are per traced session (HLO payload × executions ≈ steps);
    busbw uses the nccl-tests factor for the kind, judged against the
    link roofline when the ICI probe (or PADDLE_TPU_ICI_GBPS) is
    available. Events whose instruction has no pd.coll scope pool under
    "(gspmd)" — the partitioner-inserted collectives (dp grad
    all-reduce, tensor-parallel gathers) that no framework line emits
    directly."""
    from . import roofline, xplane

    events = xplane.collective_events_dir(trace_dir)
    instrs: Dict[str, dict] = {}
    participants = None
    for text in hlo_texts:
        instrs.update(xplane.hlo_collectives(text))
        if participants is None:
            participants = xplane.hlo_participants(text)
    if participants is None:
        try:
            import jax
            participants = jax.device_count()
        except Exception:  # noqa: BLE001 - stdlib-only callers
            participants = None

    # join: event name -> HLO instruction (exact, then base-name match:
    # the profiler may append suffixes like '%all-reduce.3.clone')
    by_site: Dict[tuple, Dict[str, float]] = {}
    for name, ev in events.items():
        info = instrs.get(name) or instrs.get(name.lstrip("%"))
        if info is None:
            base = name.lstrip("%").split(" ")[0]
            info = instrs.get(base)
        kind = ev["kind"]
        site = (info or {}).get("site")
        if site is None:
            near = (info or {}).get("near")
            site = f"(gspmd:{near})" if near else "(gspmd)"
        nbytes = (info or {}).get("bytes", 0)
        acc = by_site.setdefault((kind, site), {
            "count": 0, "bytes": 0.0, "ps": 0, "exposed_ps": 0})
        acc["count"] += 1
        acc["bytes"] += float(nbytes) * (steps or 1)
        acc["ps"] += ev["total_ps"]
        acc["exposed_ps"] += ev["exposed_ps"]

    ici = roofline.ensure_ici(probe) if (by_site or probe) else None
    n = participants or 1
    rows: List[Dict[str, Any]] = []
    for (kind, site), acc in sorted(by_site.items(),
                                    key=lambda kv: -kv[1]["ps"]):
        secs = acc["ps"] / 1e12
        algbw = (acc["bytes"] / secs / 1e9) if secs > 0 else None
        factor = xplane.busbw_factor(kind, n)
        busbw = algbw * factor if (algbw is not None and factor) else algbw
        pct = (busbw / ici) if (busbw is not None and ici) else None
        rows.append({
            "kind": kind, "site": site, "count": acc["count"],
            "bytes": acc["bytes"], "time_ms": acc["ps"] / 1e9,
            "exposed_ms": acc["exposed_ps"] / 1e9,
            "algbw_gbps": algbw, "busbw_gbps": busbw, "pct_link": pct,
            "overlap_frac": (1.0 - acc["exposed_ps"] / acc["ps"]
                             if acc["ps"] else None)})
    return {"rows": rows, "ici_gbps": ici, "participants": participants}


def busbw_by_kind(table: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """{kind: busbw_gbps} folded over a collective_table's rows (time-
    weighted across sites) — the compact per-kind form bench JSON lines
    carry."""
    if not table or not table.get("rows"):
        return {}
    acc: Dict[str, Dict[str, float]] = {}
    for r in table["rows"]:
        if r.get("busbw_gbps") is None:
            continue
        a = acc.setdefault(r["kind"], {"bw_ms": 0.0, "ms": 0.0})
        a["bw_ms"] += r["busbw_gbps"] * r["time_ms"]
        a["ms"] += r["time_ms"]
    return {k: round(a["bw_ms"] / a["ms"], 3)
            for k, a in acc.items() if a["ms"] > 0}


def exposed_summary(table: Optional[Dict[str, Any]]) \
        -> Optional[Dict[str, float]]:
    """`exposed_collective_seconds` + `overlap_fraction` folded over a
    collective_table's rows (ISSUE 9 satellite: the overlap win as one
    tracked number on every bench/scaling JSON line). Exposed time is
    collective device time covered by no concurrent compute
    (xplane.exposed_in_line); overlap_fraction = 1 - exposed/total over
    ALL collective time, so a fully hidden sync reads 1.0 and the
    monolithic end-of-trace sync reads ~0. None when the trace shows no
    collectives at all (single-device runs)."""
    rows = (table or {}).get("rows") or []
    total_ms = sum(r.get("time_ms") or 0.0 for r in rows)
    if total_ms <= 0:
        return None
    exposed_ms = sum(r.get("exposed_ms") or 0.0 for r in rows)
    return {"exposed_collective_seconds": round(exposed_ms / 1e3, 6),
            "overlap_fraction": round(1.0 - exposed_ms / total_ms, 4)}


# --- cross-host skew / straggler detection ----------------------------------

def local_snapshot() -> Dict[str, Any]:
    """This host's FleetSnapshot: the per-host scalars the skew reduce
    compares. All reads are read-only telemetry peeks — a host that never
    ran a step contributes zeros, never new series."""
    from . import telemetry

    hist = telemetry.read_histogram("input_stall_seconds") or {}
    hbm = {name: max(telemetry.read_series(name).values() or [0.0])
           for name in ("hbm_bytes_in_use", "hbm_peak_bytes")}
    # run-sentinel alert counts (ISSUE 17): series keys are
    # "rule=...,severity=..." — page-severity split out so the straggler
    # verdict can say WHICH host is alerting, not just which is slow
    alerts = telemetry.read_series("sentinel_alerts_total")
    alerts_page = sum(v for k, v in alerts.items() if "severity=page" in k)
    return {
        "host": telemetry._host_index(),
        "steps": sum(telemetry.read_series("executor_steps_total")
                     .values() or [0.0]),
        "step_time_s": telemetry.read_gauge("executor_last_step_seconds"),
        "device_duty_cycle": telemetry.read_gauge("device_duty_cycle"),
        "infeed_wait_s": hist.get("sum", 0.0),
        "collective_wait_s":
            telemetry.read_gauge("collective_exposed_seconds") or 0.0,
        "collective_time_s":
            telemetry.read_gauge("collective_time_seconds") or 0.0,
        "hbm_bytes_in_use": hbm["hbm_bytes_in_use"],
        "hbm_peak_bytes": hbm["hbm_peak_bytes"],
        "alerts_total": sum(alerts.values()) if alerts else 0.0,
        "alerts_page": alerts_page,
    }


def fleet_snapshot(local: Optional[Dict[str, Any]] = None) \
        -> Dict[str, Any]:
    """Allgather every host's FleetSnapshot and reduce: max/median
    step-time skew, the slowest host, and what it is slow ON (compute vs
    infeed vs collective-wait, by largest excess over the fleet median).
    Publishes `fleet_step_skew` and `fleet_straggler_host`. Single-process
    runs short-circuit to a skew of 1.0 with themselves as the (vacuous)
    straggler."""
    from . import telemetry
    from .parallel import multihost

    local = dict(local or local_snapshot())
    payloads = multihost.allgather_bytes(
        json.dumps(local, sort_keys=True).encode("utf-8"))
    hosts = []
    for p in payloads:
        try:
            hosts.append(json.loads(p.decode("utf-8")))
        except Exception:  # noqa: BLE001 - a corrupt peer can't kill us
            continue
    if not hosts:
        hosts = [local]

    def _med(vals):
        vals = sorted(vals)
        k = len(vals) // 2
        return vals[k] if len(vals) % 2 else 0.5 * (vals[k - 1] + vals[k])

    times = [float(h.get("step_time_s") or 0.0) for h in hosts]
    med = _med(times)
    mx = max(times)
    skew = (mx / med) if med > 0 else 1.0
    slow = hosts[times.index(mx)]

    # attribution: which badput component exceeds the fleet median most
    cause, excess = "compute", 0.0
    for key, label in (("infeed_wait_s", "infeed"),
                       ("collective_wait_s", "collective-wait")):
        vals = [float(h.get(key) or 0.0) for h in hosts]
        d = float(slow.get(key) or 0.0) - _med(vals)
        if d > excess:
            cause, excess = label, d
    # sentinel alert roll-up: the host with the most alerts, so a skew
    # verdict can name the host that is also statistically anomalous
    alert_counts = [float(h.get("alerts_total") or 0.0) for h in hosts]
    alerting = (hosts[alert_counts.index(max(alert_counts))]
                if max(alert_counts, default=0.0) > 0 else None)
    out = {
        "hosts": hosts, "n_hosts": len(hosts),
        "median_step_s": med, "max_step_s": mx,
        "step_skew": max(skew, 1.0),
        "straggler": {"host": slow.get("host", 0), "cause": cause,
                      "alerts_total": float(slow.get("alerts_total")
                                            or 0.0)},
        "alerting_host": (None if alerting is None
                          else {"host": alerting.get("host", 0),
                                "alerts_total":
                                    float(alerting.get("alerts_total")
                                          or 0.0),
                                "alerts_page":
                                    float(alerting.get("alerts_page")
                                          or 0.0)}),
    }
    telemetry.gauge(
        "fleet_step_skew",
        "max/median step-time ratio across hosts (1.0 = no skew)").set(
            out["step_skew"])
    telemetry.gauge(
        "fleet_straggler_host",
        "host index with the slowest last step").set(
            float(out["straggler"]["host"]))
    return out


# --- goodput accounting ------------------------------------------------------

_RUN_KINDS = ("run", "run_window")


def goodput_report(events=None, now: Optional[float] = None,
                   input_stall_s: Optional[float] = None,
                   collective_wait_s: Optional[float] = None) \
        -> Optional[Dict[str, Any]]:
    """The run-level goodput ledger. Wall span = first run start to last
    run end (telemetry event `mono` stamps); split into:

        productive       execute time minus exposed collective wait
        compile          run.compile_s sums (the `compile` events are
                         nested inside run wall time — counting both
                         would double-price a trace)
        checkpoint_save  multihost 'checkpoint' op=save events, falling
                         back to io.py 'checkpoint_save' (which nest
                         inside multihost saves — never both)
        restore          ... same for load
        input_stall      input_stall_seconds histogram sum
        collective_wait  exposed collective seconds (trace-derived)
        idle             span minus everything above (clamped ≥ 0)

    Returns None with no run events (nothing ran — no denominator).
    Publishes `goodput_fraction` + `goodput_seconds{bucket}`."""
    from . import telemetry

    events = list(telemetry.recent_events() if events is None else events)
    runs = [e for e in events if e.get("kind") in _RUN_KINDS]
    if not runs:
        return None
    starts = [e["mono"] - float(e.get("seconds") or 0.0) for e in runs]
    ends = [e["mono"] for e in runs]
    span = (now if now is not None else max(ends)) - min(starts)
    span = max(span, 1e-9)

    execute = sum(float(e.get("execute_s") or 0.0) for e in runs)
    compile_ = sum(float(e.get("compile_s") or 0.0) for e in runs)

    # checkpoint badput: prefer the multihost wall-clock markers; io.py's
    # save/load events nest inside them, so fall back only when no
    # multihost marker of that direction exists
    mh = [e for e in events if e.get("kind") == "checkpoint"]
    ck_save = sum(float(e.get("seconds") or 0.0) for e in mh
                  if e.get("op") == "save")
    ck_load = sum(float(e.get("seconds") or 0.0) for e in mh
                  if e.get("op") == "load")
    if not any(e.get("op") == "save" for e in mh):
        ck_save = sum(float(e.get("seconds") or 0.0) for e in events
                      if e.get("kind") == "checkpoint_save")
    if not any(e.get("op") == "load" for e in mh):
        ck_load = sum(float(e.get("seconds") or 0.0) for e in events
                      if e.get("kind") == "checkpoint_load")

    if input_stall_s is None:
        hist = telemetry.read_histogram("input_stall_seconds") or {}
        input_stall_s = float(hist.get("sum", 0.0))
    if collective_wait_s is None:
        collective_wait_s = float(
            telemetry.read_gauge("collective_exposed_seconds") or 0.0)
    collective_wait_s = min(collective_wait_s, execute)

    productive = max(execute - collective_wait_s, 0.0)
    buckets = {
        "productive": productive,
        "compile": compile_,
        "checkpoint_save": ck_save,
        "restore": ck_load,
        "input_stall": input_stall_s,
        "collective_wait": collective_wait_s,
    }
    accounted = sum(buckets.values())
    buckets["idle"] = max(span - accounted, 0.0)
    goodput = min(productive / span, 1.0)

    g = telemetry.gauge("goodput_fraction",
                        "productive step time / wall span of the run")
    g.set(goodput)
    bg = telemetry.gauge("goodput_seconds",
                         "wall seconds per goodput/badput bucket",
                         labels=("bucket",))
    for b, v in buckets.items():
        bg.labels(bucket=b).set(v)
    return {"span_s": span, "goodput_fraction": goodput,
            "buckets": buckets, "runs": len(runs)}


# --- rendering ---------------------------------------------------------------

def format_goodput(gp: Optional[Dict[str, Any]]) -> List[str]:
    if not gp:
        return ["[goodput] no run events recorded"]
    lines = ["[goodput] {:.1%} productive over {:.2f}s wall "
             "({} runs)".format(gp["goodput_fraction"], gp["span_s"],
                                gp["runs"])]
    span = gp["span_s"]
    for bucket, v in sorted(gp["buckets"].items(), key=lambda kv: -kv[1]):
        lines.append("[goodput]   {:16s} {:9.3f}s {:6.1%}".format(
            bucket, v, v / span))
    return lines


def format_fleet(snap: Dict[str, Any]) -> str:
    s = snap["straggler"]
    line = ("[fleet] hosts {} | step skew {:.2f}x (median {:.4f}s, max "
            "{:.4f}s) | straggler host {} ({})".format(
                snap["n_hosts"], snap["step_skew"], snap["median_step_s"],
                snap["max_step_s"], s["host"], s["cause"]))
    a = snap.get("alerting_host")
    if a:
        line += " | alerting host {} ({:.0f} alert(s))".format(
            a["host"], a["alerts_total"])
    return line


# --- one-call capture --------------------------------------------------------

def capture(run, steps: int = 3, probe: bool = True) \
        -> Optional[Dict[str, Any]]:
    """Run `run()` `steps` times inside a silent traced session and return
    {"roofline", "collectives", "goodput", "snapshot"} — the fleet
    analogue of roofline.capture (which it reuses; the roofline report
    already embeds the collective table). None when tracing failed."""
    from . import profiler as profiler_mod

    tmp = tempfile.mkdtemp(prefix="pd_fleet_")
    report = None
    try:
        profiler_mod.start_profiler(trace_dir=tmp)
        try:
            for _ in range(steps):
                run()
        finally:
            report = profiler_mod.finish_trace_report(probe=probe)
    except Exception:  # noqa: BLE001 - observability must not kill the run
        report = None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if report is None:
        return None
    try:
        snap = fleet_snapshot()
    except Exception:  # noqa: BLE001
        snap = None
    return {"roofline": report,
            "collectives": report.get("collectives"),
            "goodput": goodput_report(),
            "snapshot": snap}
