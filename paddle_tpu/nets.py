"""Composite network helpers (reference: python/paddle/fluid/nets.py)."""

from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, param_attr=None,
                         pool_type="max", use_cudnn=True, use_mkldnn=False):
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act)
    return layers.pool2d(input=conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True,
                   use_mkldnn=False, is_test=False):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(obj):
        if isinstance(obj, (list, tuple)):
            assert len(obj) == len(conv_num_filter)
            return list(obj)
        return [obj] * len(conv_num_filter)

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(input=tmp, num_filters=conv_num_filter[i],
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act, is_test=is_test)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate,
                                     is_test=is_test)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(x=a, y=layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (reference nets.py:180).
    On TPU this is a chain of MXU matmuls; XLA fuses scale+softmax."""
    head_dim = queries.shape[-1] // num_heads

    def _split_heads(x):
        if num_heads == 1:
            return x
        reshaped = layers.reshape(
            x, shape=[0, x.shape[1] if x.shape[1] and x.shape[1] > 0 else -1,
                      num_heads, head_dim])
        return layers.transpose(reshaped, perm=[0, 2, 1, 3])

    def _merge_heads(x):
        if num_heads == 1:
            return x
        t = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            t, shape=[0, 0, num_heads * head_dim] if None in t.shape
            else [t.shape[0], t.shape[1], num_heads * head_dim])

    q, k, v = _split_heads(queries), _split_heads(keys), _split_heads(values)
    scaled_q = layers.scale(q, scale=head_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx_multiheads = layers.matmul(weights, v)
    return _merge_heads(ctx_multiheads)
