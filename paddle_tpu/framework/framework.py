"""Program/Block/Variable/Operator graph model.

TPU-native equivalent of the reference's Python front-end graph classes
(reference: python/paddle/fluid/framework.py:117 Variable, :361 Operator,
:644 Block, :940 Program, :1118 Parameter, :1176-1257 default program guards).
The user builds a Program whose desc is the serializable IR in `desc.py`;
execution compiles blocks to XLA (see executor.py) instead of interpreting
ops one-by-one.
"""

from __future__ import annotations

import contextlib
import copy
import os
import sys
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from . import unique_name
from .desc import (BlockDesc, BlockRef, BlocksRef, OpDesc, ProgramDesc,
                   VarDesc, VarType)

__all__ = [
    "Variable",
    "Operator",
    "Block",
    "Program",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "switch_main_program",
    "switch_startup_program",
    "program_guard",
    "grad_var_name",
    "GRAD_VAR_SUFFIX",
]

GRAD_VAR_SUFFIX = "@GRAD"


def op_external_reads(program, op) -> set:
    """Names an op reads, including everything its sub-blocks read from
    outside themselves (reference prune.cc:181 recurses into block attrs —
    a while/conditional_block depends on its upstream producers even when
    the root op desc only lists e.g. Cond)."""
    reads = set(op.input_arg_names)
    sub_idxs = []
    for a in op.desc.attrs.values():
        if isinstance(a, BlockRef):
            sub_idxs.append(a.idx)
        elif isinstance(a, BlocksRef):
            sub_idxs.extend(a.idxs)
    seen = set()
    while sub_idxs:
        si = sub_idxs.pop()
        if si in seen:
            continue
        seen.add(si)
        sub = program.block(si)
        produced = set()
        for sop in sub.ops:
            for name in sop.input_arg_names:
                if name not in produced and not sub.desc.has_var(name):
                    reads.add(name)
            produced.update(sop.output_arg_names)
            for a in sop.desc.attrs.values():
                if isinstance(a, BlockRef):
                    sub_idxs.append(a.idx)
                elif isinstance(a, BlocksRef):
                    sub_idxs.extend(a.idxs)
    return reads


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_frame() -> Optional[str]:
    """file:line of the first stack frame outside paddle_tpu (cheap: walks
    frames, no traceback objects)."""
    try:
        f = sys._getframe(2)
    except ValueError:
        return None
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) or os.sep + "tests" + os.sep in fn:
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return None


def grad_var_name(name: str) -> str:
    return name + GRAD_VAR_SUFFIX


_np_dtype_names = {
    "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64", "uint8", "bool",
}


def convert_dtype(dtype) -> str:
    """Normalize a dtype spec (np.dtype, str, jnp dtype) to a canonical name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        name = dtype
    else:
        name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    if name not in _np_dtype_names:
        # handles things like np.float32 type objects
        name = np.dtype(name).name
    assert name in _np_dtype_names, f"unsupported dtype {dtype!r}"
    return name


class Variable:
    """Compile-time variable handle inside a Block (reference framework.py:117).

    Holds no data; runtime values live in a Scope (executor.py). Math operator
    overloading is patched on by layers/math_op_patch.py.
    """

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Iterable[int]] = None,
        dtype=None,
        lod_level: Optional[int] = None,
        type: VarType = VarType.LOD_TENSOR,
        persistable: Optional[bool] = None,
        stop_gradient: bool = False,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        if block.desc.has_var(name):
            # Re-opening an existing var (e.g. startup/main program share
            # parameter names): merge, verifying compatible metadata.
            d = block.desc.var(name)
            if shape is not None and d.shape is not None:
                assert list(shape) == list(d.shape), (
                    f"Variable {name} re-declared with shape {list(shape)} != {d.shape}")
            if shape is not None:
                d.shape = list(shape)
            if dtype is not None:
                d.dtype = convert_dtype(dtype)
            if lod_level is not None:
                d.lod_level = lod_level
            if persistable is not None:
                d.persistable = persistable
        else:
            d = VarDesc(
                name=name,
                type=type,
                dtype=convert_dtype(dtype),
                shape=list(shape) if shape is not None else None,
                lod_level=lod_level or 0,
                persistable=bool(persistable),
                stop_gradient=stop_gradient,
            )
            block.desc.vars[name] = d
        self.desc = d
        self.stop_gradient = stop_gradient
        block.vars[name] = self

    # --- metadata accessors -------------------------------------------------
    @property
    def name(self) -> str:
        return self.desc.name

    @property
    def shape(self):
        return tuple(self.desc.shape) if self.desc.shape is not None else None

    @property
    def dtype(self) -> str:
        return self.desc.dtype

    @property
    def lod_level(self) -> int:
        return self.desc.lod_level

    @property
    def type(self) -> VarType:
        return self.desc.type

    @property
    def persistable(self) -> bool:
        return self.desc.persistable

    @persistable.setter
    def persistable(self, p: bool):
        self.desc.persistable = p
        # invalidates the executor's cached program analysis (persistable
        # map) and jit cache — the run signature changes with this flag
        self.block.program._version += 1

    def __str__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, type={self.type.value})")

    __repr__ = __str__


class Operator:
    """Compile-time operator inside a Block (reference framework.py:361).

    Appending an operator immediately runs compile-time shape inference via
    the op registry (the reference does this through C++ InferShape at desc
    build time).
    """

    def __init__(self, block: "Block", desc: OpDesc):
        self.block = block
        self.desc = desc
        # Python creation site (first frame outside paddle_tpu): the
        # CustomStackTrace analogue (reference utils/CustomStackTrace.h
        # dumps the layer stack on crash) — executor error messages point
        # at the user line that built the failing op.
        self.creation_site = _user_frame()

    @property
    def type(self) -> str:
        return self.desc.type

    @property
    def input_arg_names(self):
        return self.desc.input_arg_names()

    @property
    def output_arg_names(self):
        return self.desc.output_arg_names()

    def input(self, slot):
        return self.desc.input(slot)

    def output(self, slot):
        return self.desc.output(slot)

    def attr(self, name, default=None):
        return self.desc.attr(name, default)

    def set_attr(self, name, val):
        self.desc.attrs[name] = val

    def __str__(self):
        ins = {k: v for k, v in self.desc.inputs.items()}
        outs = {k: v for k, v in self.desc.outputs.items()}
        return f"Op(type={self.type}, inputs={ins}, outputs={outs})"

    __repr__ = __str__


class Block:
    """An ordered op list plus a var table (reference framework.py:644)."""

    def __init__(self, program: "Program", idx: int):
        self.program = program
        self.desc: BlockDesc = program.desc.block(idx)
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def idx(self) -> int:
        return self.desc.idx

    @property
    def parent_idx(self) -> int:
        return self.desc.parent_idx

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.desc.parent_idx < 0:
            return None
        return self.program.block(self.desc.parent_idx)

    # --- vars ---------------------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        return Variable(self, **kwargs)

    def create_parameter(self, **kwargs) -> "Parameter":
        # Parameters always live in the global (root) block, matching the
        # reference's global-block parameter placement.
        gblock = self.program.global_block()
        return Parameter(gblock, **kwargs)

    def has_var(self, name: str) -> bool:
        return name in self.vars or self.desc.has_var(name)

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is not None:
            return v
        if self.desc.has_var(name):
            # materialize a wrapper for a desc-only var (e.g. after clone)
            d = self.desc.vars[name]
            v = Variable.__new__(Variable)
            v.block = self
            v.desc = d
            v.stop_gradient = d.stop_gradient
            self.vars[name] = v
            return v
        raise ValueError(f"Variable {name} not found in block {self.idx}")

    def var_recursive(self, name: str) -> Variable:
        b: Optional[Block] = self
        while b is not None:
            if b.has_var(name):
                return b.var(name)
            b = b.parent_block
        raise ValueError(f"Variable {name} not found in block chain from {self.idx}")

    def has_var_recursive(self, name: str) -> bool:
        b: Optional[Block] = self
        while b is not None:
            if b.has_var(name):
                return True
            b = b.parent_block
        return False

    def all_parameters(self) -> List["Parameter"]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # --- ops ----------------------------------------------------------------
    def _make_op(self, type: str, inputs=None, outputs=None, attrs=None) -> OpDesc:
        def norm(d):
            out = {}
            for k, v in (d or {}).items():
                if v is None:
                    continue
                if isinstance(v, (Variable, str)):
                    v = [v]
                out[k] = [x.name if isinstance(x, Variable) else x for x in v]
            return out

        return OpDesc(type=type, inputs=norm(inputs), outputs=norm(outputs),
                      attrs=dict(attrs or {}))

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        desc = self._make_op(type, inputs, outputs, attrs)
        op = Operator(self, desc)
        self.desc.ops.append(desc)
        self.ops.append(op)
        self.program._version += 1
        self._infer_shape(op)
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        desc = self._make_op(type, inputs, outputs, attrs)
        op = Operator(self, desc)
        self.desc.ops.insert(0, desc)
        self.ops.insert(0, op)
        self.program._version += 1
        self._infer_shape(op)
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None,
                  attrs=None) -> Operator:
        desc = self._make_op(type, inputs, outputs, attrs)
        op = Operator(self, desc)
        self.desc.ops.insert(index, desc)
        self.ops.insert(index, op)
        self.program._version += 1
        self._infer_shape(op)
        return op

    def remove_op(self, index: int):
        del self.desc.ops[index]
        del self.ops[index]
        self.program._version += 1

    def _infer_shape(self, op: Operator):
        from ..ops import registry  # local import to avoid cycle
        opdef = registry.try_get(op.type)
        if opdef is None:
            raise ValueError(f"Operator type '{op.type}' is not registered")
        if opdef.infer_shape is not None:
            opdef.infer_shape(op, self)

    def _sync_ops(self):
        """Rebuild Operator wrappers from desc (after clone/deserialize)."""
        self.ops = [Operator(self, d) for d in self.desc.ops]
        for name in list(self.desc.vars):
            self.var(name)


class Program:
    """A whole computation: list of blocks (reference framework.py:940)."""

    def __init__(self):
        self.desc = ProgramDesc()
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self._seed = 0
        # bumped on every op append/remove so the executor's compile cache
        # never serves a stale trace for a mutated program
        self._version = 0
        # device mesh for SPMD execution (parallel/transpiler.py)
        self._mesh = None
        # populated by append_backward: grad var name <-> fwd var mapping
        self.grad_info_map: Dict[str, Any] = {}

    # --- seeds --------------------------------------------------------------
    @property
    def random_seed(self) -> int:
        return self._seed

    @random_seed.setter
    def random_seed(self, s: int):
        self._seed = int(s)

    # --- block management ---------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.desc.append_block(parent)
        b = Block(self, len(self.desc.blocks) - 1)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    # --- whole-program ops --------------------------------------------------
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program (reference framework.py Program.clone).

        for_test=True flips training-only behavior off (e.g. dropout/batch_norm
        is_test attr), mirroring the reference's inference_optimize+clone use.
        """
        p = Program()
        p.desc = ProgramDesc.from_json(self.desc.to_json())
        p._seed = self._seed
        # dynamic execution attributes ride along (the reference keeps these
        # in the desc; here they are Python-side program state): mesh tag,
        # AMP policy, bound reader pipelines
        p._mesh = getattr(self, "_mesh", None)
        for attr in ("_amp_dtype", "_amp_level", "_pipeline_readers",
                     "_param_shardings", "_feed_shardings", "_sharded_tables",
                     # observability state: telemetry side-fetch marks, loss
                     # names recorded by append_backward, inspector probe
                     # sites / audit / internal-run marker — all describe the
                     # desc being copied, so they ride along (dict/list
                     # values shallow-copied so mutating the clone's map
                     # never leaks back)
                     "_telemetry_fetch_extra", "_loss_names", "_probe_sites",
                     "_probe_parent", "_grad_audit", "_inspector_internal"):
            if hasattr(self, attr):
                val = getattr(self, attr)
                if isinstance(val, (dict, list)):
                    val = copy.copy(val)
                setattr(p, attr, val)
        if self.grad_info_map:
            p.grad_info_map = dict(self.grad_info_map)
        p.blocks = [Block(p, i) for i in range(len(p.desc.blocks))]
        for b in p.blocks:
            b._sync_ops()
            # preserve Parameter-ness
            src = self.blocks[b.idx]
            for name, v in src.vars.items():
                if isinstance(v, Parameter) and name in b.vars:
                    pv = b.vars[name]
                    param = Parameter.__new__(Parameter)
                    param.__dict__.update(pv.__dict__)
                    param.trainable = v.trainable
                    param.optimize_attr = copy.copy(v.optimize_attr)
                    param.regularizer = v.regularizer
                    param.gradient_clip_attr = v.gradient_clip_attr
                    param.do_model_average = v.do_model_average
                    b.vars[name] = param
        if for_test:
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in op.desc.attrs:
                        op.set_attr("is_test", True)
        p.current_block_idx = 0
        return p

    def prune(self, feeds: List[str], fetches: List[str]) -> "Program":
        """Dead-op elimination from fetch targets (reference prune.cc:181).

        Keeps, in the root block, only ops on a path to `fetches` given that
        `feeds` are externally provided.

        Backward/optimize-role ops reached only through an in-place
        persistable update are dropped: an optimizer op writes ParamOut
        aliasing the parameter, so a fetch built after minimize() sees it
        as the parameter's producer and the reverse walk would drag the
        whole training tail — gradients, moments, beta pows — into the
        inference slice, leaving dead opt-state persistables the dead-var
        pass then flags. The pre-update value is what an inference slice
        wants; the parameter stays a state leaf. A training-role op that
        is the sole producer of a needed NON-persistable (an explicitly
        fetched gradient) is still kept.
        """
        pruned = self.clone()
        block = pruned.global_block()

        def op_reads(op):
            return op_external_reads(pruned, op)

        def _persistable(name):
            return block.desc.has_var(name) and \
                block.desc.var(name).persistable

        needed = set(fetches)
        keep: List[int] = []
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            hit = needed & set(op.output_arg_names)
            if hit:
                if op.desc.attrs.get("op_role") in ("backward", "optimize") \
                        and all(_persistable(n) for n in hit):
                    continue
                keep.append(i)
                for name in op_reads(op):
                    if name not in feeds:
                        needed.add(name)
        keep.reverse()
        block.desc.ops = [block.desc.ops[i] for i in keep]
        block._sync_ops()
        # drop root vars no longer referenced (sub-block vars stay put)
        used = set(feeds) | set(fetches)
        for op in block.ops:
            used |= op_reads(op) | set(op.output_arg_names)
        for name in list(block.desc.vars):
            if name not in used:
                del block.desc.vars[name]
                block.vars.pop(name, None)
        return pruned

    def to_json(self) -> str:
        return self.desc.to_json()

    @staticmethod
    def from_json(s: str) -> "Program":
        p = Program()
        p.desc = ProgramDesc.from_json(s)
        p.blocks = [Block(p, i) for i in range(len(p.desc.blocks))]
        for b in p.blocks:
            b._sync_ops()
        return p

    def __str__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.parent_idx}):")
            for name, v in sorted(b.desc.vars.items()):
                tag = " [persistable]" if v.persistable else ""
                lines.append(f"  var {name}: {v.dtype}{v.shape}{tag}")
            for op in b.ops:
                lines.append(f"  {op}")
        return "\n".join(lines)


class Parameter(Variable):
    """A persistable, trainable variable (reference framework.py:1118)."""

    def __init__(self, block: Block, shape=None, dtype=None, **kwargs):
        assert shape is not None, "Parameter requires a fully-known shape"
        assert all(s > 0 for s in shape), f"Parameter shape must be static, got {shape}"
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        super().__init__(block, shape=shape, dtype=dtype, persistable=True, **kwargs)


# --- default programs -------------------------------------------------------

_main_program = Program()
_startup_program = Program()


@contextlib.contextmanager
def in_block(program: Program, block_idx: int):
    """Temporarily build ops into `block_idx` of `program` — the shared
    idiom for control-flow builders that must emit setup ops into the
    PARENT block while the sub-block is current (DynamicRNN memory init,
    v2 beam_search boot state: those ops run before the loop op, which
    the enclosing context appends only on exit)."""
    cur = program.current_block_idx
    program.current_block_idx = block_idx
    try:
        yield program.current_block()
    finally:
        program.current_block_idx = cur


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)
