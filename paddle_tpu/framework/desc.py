"""Serializable program IR: ProgramDesc / BlockDesc / OpDesc / VarDesc.

TPU-native rebuild of the reference's protobuf IR schema
(reference: paddle/fluid/framework/framework.proto:19,34,64,94-176). The
semantics match — a program is a list of blocks; a block owns named variables
and an ordered op list; ops name their inputs/outputs through parameter slots
(each slot holds a list of variable names) and carry typed attributes,
including references to sub-blocks for control flow. Rather than protobuf we
use plain dataclasses with a stable JSON round-trip, which is all the
serialization surface the framework needs (save/load_inference_model).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class VarType(enum.Enum):
    # Tensor-ish types (reference framework.proto:94-176).
    LOD_TENSOR = "lod_tensor"
    SELECTED_ROWS = "selected_rows"
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    LOD_RANK_TABLE = "lod_rank_table"
    # Executor plumbing types.
    FEED_MINIBATCH = "feed_minibatch"
    FETCH_LIST = "fetch_list"
    STEP_SCOPES = "step_scopes"
    READER = "reader"
    RAW = "raw"


# Attribute values are restricted to JSON-serializable shapes: bool, int,
# float, str, lists/tuples thereof, and ints naming sub-blocks (reference
# OpDesc::Attr with BlockDesc attrs, framework.proto:34-63). Block references
# are stored as {"__block__": idx} and tuples as {"__tuple__": [...]} so
# round-trips are unambiguous — a tuple-valued attr (e.g. an axes pair an op
# compares with `== (0, 1)`) must come back a tuple, not a list.
@dataclass
class BlockRef:
    idx: int


@dataclass
class BlocksRef:
    idxs: List[int]


@dataclass
class VarDesc:
    name: str
    type: VarType = VarType.LOD_TENSOR
    dtype: str = "float32"           # numpy dtype name; bf16 spelled "bfloat16"
    shape: Optional[List[int]] = None  # -1 = unknown/dynamic (batch) dim
    lod_level: int = 0
    persistable: bool = False
    stop_gradient: bool = False

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["type"] = self.type.value
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "VarDesc":
        d = dict(d)
        d["type"] = VarType(d["type"])
        return VarDesc(**d)


def _attr_to_json(v: Any) -> Any:
    if isinstance(v, BlockRef):
        return {"__block__": v.idx}
    if isinstance(v, BlocksRef):
        return {"__blocks__": v.idxs}
    if isinstance(v, tuple):
        return {"__tuple__": [_attr_to_json(x) for x in v]}
    if isinstance(v, list):
        return [_attr_to_json(x) for x in v]
    return v


def _attr_from_json(v: Any) -> Any:
    if isinstance(v, dict) and "__block__" in v:
        return BlockRef(v["__block__"])
    if isinstance(v, dict) and "__blocks__" in v:
        return BlocksRef(v["__blocks__"])
    if isinstance(v, dict) and "__tuple__" in v:
        return tuple(_attr_from_json(x) for x in v["__tuple__"])
    if isinstance(v, list):
        # pre-__tuple__ JSON stored tuples as bare lists; those load as
        # lists (the old, lossy behavior) — only new dumps round-trip
        return [_attr_from_json(x) for x in v]
    return v


@dataclass
class OpDesc:
    type: str
    # slot name -> list of variable names (reference OpDesc.Var, framework.proto:40)
    inputs: Dict[str, List[str]] = field(default_factory=dict)
    outputs: Dict[str, List[str]] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def input_arg_names(self) -> List[str]:
        return [n for names in self.inputs.values() for n in names]

    def output_arg_names(self) -> List[str]:
        return [n for names in self.outputs.values() for n in names]

    def attr(self, name: str, default: Any = None) -> Any:
        return self.attrs.get(name, default)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": {k: _attr_to_json(v) for k, v in self.attrs.items()},
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "OpDesc":
        return OpDesc(
            type=d["type"],
            inputs={k: list(v) for k, v in d.get("inputs", {}).items()},
            outputs={k: list(v) for k, v in d.get("outputs", {}).items()},
            attrs={k: _attr_from_json(v) for k, v in d.get("attrs", {}).items()},
        )


@dataclass
class BlockDesc:
    idx: int = 0
    parent_idx: int = -1
    vars: Dict[str, VarDesc] = field(default_factory=dict)
    ops: List[OpDesc] = field(default_factory=list)
    # forward block this block is the grad of (-1 = none), mirrors
    # reference BlockDesc.forward_block_idx
    forward_block_idx: int = -1

    def var(self, name: str) -> VarDesc:
        return self.vars[name]

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def to_dict(self) -> Dict[str, Any]:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": {k: v.to_dict() for k, v in self.vars.items()},
            "ops": [o.to_dict() for o in self.ops],
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "BlockDesc":
        return BlockDesc(
            idx=d["idx"],
            parent_idx=d["parent_idx"],
            forward_block_idx=d.get("forward_block_idx", -1),
            vars={k: VarDesc.from_dict(v) for k, v in d["vars"].items()},
            ops=[OpDesc.from_dict(o) for o in d["ops"]],
        )


@dataclass
class ProgramDesc:
    blocks: List[BlockDesc] = field(default_factory=lambda: [BlockDesc(idx=0)])
    version: int = 1

    def block(self, idx: int) -> BlockDesc:
        return self.blocks[idx]

    def append_block(self, parent_idx: int) -> BlockDesc:
        b = BlockDesc(idx=len(self.blocks), parent_idx=parent_idx)
        self.blocks.append(b)
        return b

    def to_json(self) -> str:
        return json.dumps(
            {"version": self.version, "blocks": [b.to_dict() for b in self.blocks]}
        )

    @staticmethod
    def from_json(s: str) -> "ProgramDesc":
        d = json.loads(s)
        return ProgramDesc(
            version=d.get("version", 1),
            blocks=[BlockDesc.from_dict(b) for b in d["blocks"]],
        )
