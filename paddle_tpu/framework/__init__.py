from .desc import (BlockDesc, BlockRef, BlocksRef, OpDesc, ProgramDesc,
                   VarDesc, VarType)
from .framework import (Block, Operator, Parameter, Program, Variable,
                        convert_dtype, default_main_program,
                        default_startup_program, grad_var_name, program_guard,
                        switch_main_program, switch_startup_program)
from . import unique_name
