/* C inference API for the TPU-native framework.
 *
 * Parity surface for the reference's capi (reference:
 * paddle/capi/gradient_machine.h:36 create_for_inference_with_parameters,
 * :73 forward; paddle/capi/error.h paddle_error): load a model saved by
 * fluid.io.save_inference_model and run forward passes from C/C++.
 *
 * The reference's C API fronts its C++ GradientMachine; here the runtime is
 * the XLA executor, reached through an embedded CPython interpreter (the
 * same embedding technique the reference uses for PyDataProvider2). The
 * first call to paddle_tpu_init() boots the interpreter; model handles are
 * opaque and thread-safe at the GIL's granularity.
 */
#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PD_NO_ERROR = 0,
  PD_NULLPTR = 1,
  PD_OUT_OF_RANGE = 2,
  PD_PROTOBUF_ERROR = 3,
  PD_NOT_SUPPORTED = 4,
  PD_UNDEFINED_ERROR = 5,
} paddle_error;

typedef void* paddle_tpu_machine;

/* Boot the embedded interpreter (idempotent). Honors PYTHONPATH. */
paddle_error paddle_tpu_init(void);

/* Create an inference machine from a save_inference_model directory
 * (reference create_for_inference_with_parameters semantics: topology +
 * parameters in one artifact). */
paddle_error paddle_tpu_machine_create(paddle_tpu_machine* machine,
                                       const char* model_dir);

/* Stage one named input (row-major float32). */
paddle_error paddle_tpu_machine_set_input(paddle_tpu_machine machine,
                                          const char* name,
                                          const float* data,
                                          const int64_t* dims, int ndim);

/* Element types for typed inputs (reference paddle_arguments carried both
 * value matrices and integer id vectors — capi/arguments.h). */
typedef enum {
  PD_DTYPE_FLOAT32 = 0,
  PD_DTYPE_INT64 = 1,
  PD_DTYPE_INT32 = 2,
} paddle_tpu_dtype;

/* Stage one named input of any supported dtype (row-major). */
paddle_error paddle_tpu_machine_set_input_typed(paddle_tpu_machine machine,
                                                const char* name,
                                                const void* data,
                                                paddle_tpu_dtype dtype,
                                                const int64_t* dims,
                                                int ndim);

/* Attach level-1 LoD offsets to a previously staged input: `offsets` is
 * the reference's sequence_start_positions vector (n monotonically
 * increasing values starting at 0, last == rows of the staged tensor —
 * reference capi/arguments.h paddle_arguments_set_sequence_start_pos).
 * Call after set_input[_typed] for sequence (LoD) feeds. */
paddle_error paddle_tpu_machine_set_input_lod(paddle_tpu_machine machine,
                                              const char* name,
                                              const int64_t* offsets, int n);

/* Run the forward pass over the staged inputs
 * (reference gradient_machine.h:73 forward, isTrain=false). */
paddle_error paddle_tpu_machine_forward(paddle_tpu_machine machine);

/* Number of fetch outputs of the loaded model. */
paddle_error paddle_tpu_machine_output_count(paddle_tpu_machine machine,
                                             int* count);

/* Borrowed view of output `idx`; valid until the next forward/destroy. */
paddle_error paddle_tpu_machine_get_output(paddle_tpu_machine machine,
                                           int idx, const float** data,
                                           const int64_t** dims, int* ndim);

paddle_error paddle_tpu_machine_destroy(paddle_tpu_machine machine);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H */
