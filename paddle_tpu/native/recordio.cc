// recordio: chunked, CRC-checked record file format + C ABI.
//
// Native equivalent of the reference's RecordIO implementation
// (reference: paddle/fluid/recordio/header.h:25, chunk.h:26, writer.cc,
// scanner.cc — a chunk = header {magic, checksum, compressor, length} +
// records). This is a fresh implementation with the same capabilities:
// append-only writer with chunking, sequential scanner, per-chunk CRC32,
// optional zlib compression. Wire format (little-endian):
//
//   file   := chunk*
//   chunk  := magic:u32 ('P','T','R','0') | compressor:u32 | num_records:u32
//             | raw_len:u32 | stored_len:u32 | crc32(payload):u32 | payload
//   payload (after decompression) := { rec_len:u32 | bytes }*
//
// Exposed through a minimal C ABI consumed via ctypes
// (python: paddle_tpu/recordio.py). No pybind11 in this image.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x30525450u;  // "PTR0"
constexpr uint32_t kNoCompress = 0;
constexpr uint32_t kZlib = 1;

struct Chunk {
  std::vector<std::string> records;
  size_t num_bytes = 0;

  void Clear() {
    records.clear();
    num_bytes = 0;
  }
};

bool WriteChunk(FILE* f, const Chunk& c, uint32_t compressor) {
  std::string payload;
  payload.reserve(c.num_bytes + c.records.size() * 4);
  for (const auto& r : c.records) {
    uint32_t len = static_cast<uint32_t>(r.size());
    payload.append(reinterpret_cast<const char*>(&len), 4);
    payload.append(r);
  }
  std::string stored = payload;
  if (compressor == kZlib) {
    uLongf bound = compressBound(payload.size());
    stored.resize(bound);
    if (compress2(reinterpret_cast<Bytef*>(&stored[0]), &bound,
                  reinterpret_cast<const Bytef*>(payload.data()),
                  payload.size(), Z_DEFAULT_COMPRESSION) != Z_OK) {
      return false;
    }
    stored.resize(bound);
  }
  uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
                       stored.size());
  uint32_t head[6] = {kMagic, compressor,
                      static_cast<uint32_t>(c.records.size()),
                      static_cast<uint32_t>(payload.size()),
                      static_cast<uint32_t>(stored.size()), crc};
  if (fwrite(head, sizeof(head), 1, f) != 1) return false;
  if (!stored.empty() && fwrite(stored.data(), stored.size(), 1, f) != 1)
    return false;
  return true;
}

struct Writer {
  FILE* f = nullptr;
  Chunk chunk;
  uint32_t compressor = kNoCompress;
  size_t max_chunk_bytes = 1 << 20;
};

struct Scanner {
  FILE* f = nullptr;
  Chunk chunk;
  size_t cursor = 0;  // next record within chunk
  // 0 = ok/EOF, 1 = corruption (bad magic, CRC mismatch, truncated chunk,
  // decompress failure). The reference raises on corruption rather than
  // silently truncating the dataset; this flag lets Python do the same.
  int error = 0;

  bool LoadNextChunk() {
    chunk.Clear();
    cursor = 0;
    uint32_t head[6];
    long pos = ftell(f);
    if (fread(head, sizeof(head), 1, f) != 1) {
      // clean EOF only if the stream ended exactly on a chunk boundary;
      // a partial header means the file was truncated mid-chunk
      if (!feof(f) || ftell(f) != pos) error = 1;
      return false;
    }
    if (head[0] != kMagic) {
      error = 1;
      return false;
    }
    // Validate header sizes BEFORE allocating: a corrupt-but-magic-valid
    // header could otherwise request gigabytes and throw bad_alloc across
    // the C ABI. stored_len must fit in the remaining file; raw_len is
    // capped at a sane multiple of the stored bytes.
    long here = ftell(f);
    fseek(f, 0, SEEK_END);
    long remain = ftell(f) - here;
    fseek(f, here, SEEK_SET);
    if (static_cast<long>(head[4]) > remain ||
        head[3] > (1u << 30) ||
        (head[1] == kZlib && head[4] > 0 && head[3] / head[4] > 1200)) {
      error = 1;
      return false;
    }
    std::string stored(head[4], '\0');
    if (!stored.empty() && fread(&stored[0], stored.size(), 1, f) != 1) {
      error = 1;  // header promised a payload that isn't there: truncated
      return false;
    }
    uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
                         stored.size());
    if (crc != head[5]) {
      error = 1;
      return false;
    }
    std::string payload;
    if (head[1] == kZlib) {
      payload.resize(head[3]);
      uLongf raw = head[3];
      if (uncompress(reinterpret_cast<Bytef*>(&payload[0]), &raw,
                     reinterpret_cast<const Bytef*>(stored.data()),
                     stored.size()) != Z_OK || raw != head[3]) {
        error = 1;
        return false;
      }
    } else {
      payload = std::move(stored);
    }
    size_t off = 0;
    for (uint32_t i = 0; i < head[2]; ++i) {
      if (off + 4 > payload.size()) { error = 1; return false; }
      uint32_t len;
      std::memcpy(&len, payload.data() + off, 4);
      off += 4;
      if (off + len > payload.size()) { error = 1; return false; }
      chunk.records.emplace_back(payload.data() + off, len);
      off += len;
    }
    return true;
  }
};

}  // namespace

extern "C" {

void* recordio_writer_open(const char* path, int compressor,
                           int max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer;
  w->f = f;
  w->compressor = compressor == 1 ? kZlib : kNoCompress;
  if (max_chunk_bytes > 0) w->max_chunk_bytes = max_chunk_bytes;
  return w;
}

int recordio_writer_write(void* handle, const char* data, int len) {
  auto* w = static_cast<Writer*>(handle);
  w->chunk.records.emplace_back(data, len);
  w->chunk.num_bytes += len;
  if (w->chunk.num_bytes >= w->max_chunk_bytes) {
    if (!WriteChunk(w->f, w->chunk, w->compressor)) return -1;
    w->chunk.Clear();
  }
  return 0;
}

int recordio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = 0;
  if (!w->chunk.records.empty() &&
      !WriteChunk(w->f, w->chunk, w->compressor)) {
    rc = -1;
  }
  fclose(w->f);
  delete w;
  return rc;
}

void* recordio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner;
  s->f = f;
  return s;
}

// Returns pointer to the record bytes (valid until the next call) and sets
// *len; nullptr at end-of-file or on corruption.
const char* recordio_scanner_next(void* handle, int* len) {
  auto* s = static_cast<Scanner*>(handle);
  if (s->cursor >= s->chunk.records.size()) {
    if (!s->LoadNextChunk()) return nullptr;
    if (s->chunk.records.empty()) return nullptr;
  }
  const std::string& r = s->chunk.records[s->cursor++];
  *len = static_cast<int>(r.size());
  return r.data();
}

// 1 if the scanner stopped because of corruption (CRC mismatch, bad magic,
// truncated chunk) rather than clean end-of-file.
int recordio_scanner_error(void* handle) {
  return static_cast<Scanner*>(handle)->error;
}

void recordio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
