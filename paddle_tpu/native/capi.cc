// C inference shim over the XLA executor via embedded CPython.
// See capi.h for the API contract (reference: paddle/capi/gradient_machine.h,
// paddle/capi/main.h paddle_init). Build: `make libpaddle_tpu_capi.so`.

#include "capi.h"

#include <Python.h>

#include <cstring>
#include <limits>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::once_flag g_init_flag;
bool g_init_ok = false;

struct Machine {
  PyObject* py_machine = nullptr;   // paddle_tpu.capi_backend.Machine
  // last forward's outputs, copied out of Python so the borrowed views in
  // paddle_tpu_machine_get_output stay valid without holding the GIL
  std::vector<std::vector<float>> out_data;
  std::vector<std::vector<int64_t>> out_dims;
};

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

void init_python() {
  bool fresh = !Py_IsInitialized();
  if (fresh) {
    Py_InitializeEx(0);
  }
  // Py_InitializeEx leaves this thread holding the GIL; do the warm-up
  // import directly under it (no PyGILState guard — its Release must not
  // run after the thread state is detached below).
  PyObject* mod = PyImport_ImportModule("paddle_tpu.capi_backend");
  if (mod == nullptr) {
    PyErr_Print();
    g_init_ok = false;
    return;
  }
  Py_DECREF(mod);
  g_init_ok = true;
  if (fresh) {
    // detach the GIL so every API entry (this thread included) goes
    // uniformly through PyGILState_Ensure
    PyEval_SaveThread();
  }
}

}  // namespace

extern "C" {

paddle_error paddle_tpu_init(void) {
  std::call_once(g_init_flag, init_python);
  return g_init_ok ? PD_NO_ERROR : PD_UNDEFINED_ERROR;
}

paddle_error paddle_tpu_machine_create(paddle_tpu_machine* machine,
                                       const char* model_dir) {
  if (machine == nullptr || model_dir == nullptr) return PD_NULLPTR;
  paddle_error err = paddle_tpu_init();
  if (err != PD_NO_ERROR) return err;
  Gil gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.capi_backend");
  if (mod == nullptr) {
    PyErr_Print();
    return PD_UNDEFINED_ERROR;
  }
  PyObject* obj =
      PyObject_CallMethod(mod, "Machine", "s", model_dir);
  Py_DECREF(mod);
  if (obj == nullptr) {
    PyErr_Print();
    return PD_PROTOBUF_ERROR;  // model artifact unreadable
  }
  Machine* m = new Machine();
  m->py_machine = obj;
  *machine = m;
  return PD_NO_ERROR;
}

paddle_error paddle_tpu_machine_set_input_typed(paddle_tpu_machine machine,
                                                const char* name,
                                                const void* data,
                                                paddle_tpu_dtype dtype,
                                                const int64_t* dims,
                                                int ndim) {
  if (machine == nullptr || name == nullptr || data == nullptr ||
      dims == nullptr)
    return PD_NULLPTR;
  Machine* m = static_cast<Machine*>(machine);
  if (ndim < 0) return PD_OUT_OF_RANGE;
  int64_t elem_size;
  switch (dtype) {
    case PD_DTYPE_FLOAT32:
      elem_size = sizeof(float);
      break;
    case PD_DTYPE_INT64:
      elem_size = sizeof(int64_t);
      break;
    case PD_DTYPE_INT32:
      elem_size = sizeof(int32_t);
      break;
    default:
      return PD_NOT_SUPPORTED;
  }
  int64_t numel = 1;
  for (int i = 0; i < ndim; ++i) {
    if (dims[i] < 0) return PD_OUT_OF_RANGE;
    if (dims[i] != 0 &&
        numel > std::numeric_limits<int64_t>::max() / dims[i])
      return PD_OUT_OF_RANGE;  // numel overflow
    numel *= dims[i];
  }
  if (numel > std::numeric_limits<int64_t>::max() / elem_size)
    return PD_OUT_OF_RANGE;  // byte-size overflow
  Gil gil;
  PyObject* dims_tuple = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(dims_tuple, i, PyLong_FromLongLong(dims[i]));
  PyObject* payload = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), numel * elem_size);
  PyObject* r =
      PyObject_CallMethod(m->py_machine, "set_input", "sOOi", name, payload,
                          dims_tuple, static_cast<int>(dtype));
  Py_DECREF(payload);
  Py_DECREF(dims_tuple);
  if (r == nullptr) {
    PyErr_Print();
    return PD_OUT_OF_RANGE;
  }
  Py_DECREF(r);
  return PD_NO_ERROR;
}

paddle_error paddle_tpu_machine_set_input(paddle_tpu_machine machine,
                                          const char* name,
                                          const float* data,
                                          const int64_t* dims, int ndim) {
  return paddle_tpu_machine_set_input_typed(machine, name, data,
                                            PD_DTYPE_FLOAT32, dims, ndim);
}

paddle_error paddle_tpu_machine_set_input_lod(paddle_tpu_machine machine,
                                              const char* name,
                                              const int64_t* offsets,
                                              int n) {
  if (machine == nullptr || name == nullptr || offsets == nullptr)
    return PD_NULLPTR;
  if (n < 2 || offsets[0] != 0) return PD_OUT_OF_RANGE;
  for (int i = 1; i < n; ++i)
    if (offsets[i] < offsets[i - 1]) return PD_OUT_OF_RANGE;
  Machine* m = static_cast<Machine*>(machine);
  Gil gil;
  PyObject* offs = PyTuple_New(n);
  for (int i = 0; i < n; ++i)
    PyTuple_SET_ITEM(offs, i, PyLong_FromLongLong(offsets[i]));
  PyObject* r = PyObject_CallMethod(m->py_machine, "set_input_lod", "sO",
                                    name, offs);
  Py_DECREF(offs);
  if (r == nullptr) {
    PyErr_Print();
    return PD_OUT_OF_RANGE;
  }
  Py_DECREF(r);
  return PD_NO_ERROR;
}

paddle_error paddle_tpu_machine_forward(paddle_tpu_machine machine) {
  if (machine == nullptr) return PD_NULLPTR;
  Machine* m = static_cast<Machine*>(machine);
  Gil gil;
  // forward() -> list of (bytes, dims_tuple)
  PyObject* outs = PyObject_CallMethod(m->py_machine, "forward", nullptr);
  if (outs == nullptr) {
    PyErr_Print();
    return PD_UNDEFINED_ERROR;
  }
  m->out_data.clear();
  m->out_dims.clear();
  Py_ssize_t n = PyList_Size(outs);
  if (n < 0) {  // forward() did not return a list
    PyErr_Clear();
    Py_DECREF(outs);
    return PD_UNDEFINED_ERROR;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyList_GetItem(outs, i);            // borrowed
    if (pair == nullptr || !PyTuple_Check(pair) || PyTuple_Size(pair) < 2) {
      PyErr_Clear();
      Py_DECREF(outs);
      return PD_UNDEFINED_ERROR;
    }
    PyObject* payload = PyTuple_GetItem(pair, 0);        // borrowed
    PyObject* dims = PyTuple_GetItem(pair, 1);           // borrowed
    char* buf;
    Py_ssize_t len;
    if (payload == nullptr || dims == nullptr ||
        PyBytes_AsStringAndSize(payload, &buf, &len) != 0 ||
        len % static_cast<Py_ssize_t>(sizeof(float)) != 0) {
      PyErr_Clear();
      Py_DECREF(outs);
      return PD_UNDEFINED_ERROR;
    }
    std::vector<float> vals(len / sizeof(float));
    std::memcpy(vals.data(), buf, len);
    std::vector<int64_t> shape;
    for (Py_ssize_t d = 0; d < PyTuple_Size(dims); ++d)
      shape.push_back(PyLong_AsLongLong(PyTuple_GetItem(dims, d)));
    m->out_data.push_back(std::move(vals));
    m->out_dims.push_back(std::move(shape));
  }
  Py_DECREF(outs);
  return PD_NO_ERROR;
}

paddle_error paddle_tpu_machine_output_count(paddle_tpu_machine machine,
                                             int* count) {
  if (machine == nullptr || count == nullptr) return PD_NULLPTR;
  *count = static_cast<int>(static_cast<Machine*>(machine)->out_data.size());
  return PD_NO_ERROR;
}

paddle_error paddle_tpu_machine_get_output(paddle_tpu_machine machine,
                                           int idx, const float** data,
                                           const int64_t** dims, int* ndim) {
  if (machine == nullptr || data == nullptr || dims == nullptr ||
      ndim == nullptr)
    return PD_NULLPTR;
  Machine* m = static_cast<Machine*>(machine);
  if (idx < 0 || idx >= static_cast<int>(m->out_data.size()))
    return PD_OUT_OF_RANGE;
  *data = m->out_data[idx].data();
  *dims = m->out_dims[idx].data();
  *ndim = static_cast<int>(m->out_dims[idx].size());
  return PD_NO_ERROR;
}

paddle_error paddle_tpu_machine_destroy(paddle_tpu_machine machine) {
  if (machine == nullptr) return PD_NULLPTR;
  Machine* m = static_cast<Machine*>(machine);
  {
    Gil gil;
    Py_XDECREF(m->py_machine);
  }
  delete m;
  return PD_NO_ERROR;
}

}  // extern "C"
