"""Checkpoint + inference-model save/load
(reference: python/paddle/fluid/io.py:66 save_vars, :145 save_persistables,
:234 load_persistables, :298 save_inference_model, :383 load_inference_model;
serialization of each tensor mirrors save_op.cc/load_op.cc but uses .npy —
the on-disk format is ours to define for the TPU framework).

Model directory layout matches the reference: one file per variable named by
the variable, plus `__model__` holding the serialized program."""

from __future__ import annotations

import os
import pickle
import time
from typing import List, Optional, Sequence

import numpy as np

from .executor import Executor, LoDTensor, Scope, global_scope
from .framework.framework import (Parameter, Program, Variable,
                                  default_main_program, default_startup_program)

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "get_inference_program",
]


def _is_persistable(var: Variable) -> bool:
    return var.persistable


def _is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


def save_vars(executor: Executor, dirname: str, main_program: Optional[Program]
              = None, vars: Optional[Sequence[Variable]] = None,
              predicate=None, save_file_name: Optional[str] = None):
    """Write scope values of selected vars to `dirname` (reference io.py:66).
    The executor argument is kept for API parity; values come from the
    global scope."""
    main_program = main_program or default_main_program()
    t0 = time.perf_counter()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    # beyond-HBM cached tables: the scope holds only the [cache_rows, dim]
    # hot-row slab — flush dirty slots to the host-DRAM authoritative
    # store FIRST (crash-consistency barrier: once flushed, the host slab
    # is complete even if the process dies mid-save), then checkpoint the
    # full host table in the slab's place.
    emb_cache = getattr(main_program, "_emb_cache", None)
    if emb_cache is not None:
        emb_cache.flush()
    combine = {}
    total_bytes = n_saved = 0
    for v in vars:
        val = scope.find_var(v.name)
        if emb_cache is not None:
            host = emb_cache.host_value(v.name)
            if host is not None:
                val = host
        if val is None:
            continue
        lod = None
        if isinstance(val, LoDTensor):
            lod, val = val.lod, val.array()
        arr = np.asarray(val)
        total_bytes += arr.nbytes
        n_saved += 1
        if save_file_name is None:
            _save_one(os.path.join(dirname, v.name), arr, lod)
        else:
            combine[v.name] = (arr, lod)
    if save_file_name is not None:
        with open(os.path.join(dirname, save_file_name), "wb") as f:
            pickle.dump({k: (np.asarray(a), l) for k, (a, l)
                         in combine.items()}, f)
    _record_checkpoint("save", dirname, total_bytes, n_saved,
                       time.perf_counter() - t0)


def _record_checkpoint(op: str, dirname: str, nbytes: int, n_vars: int,
                       seconds: Optional[float] = None):
    """Checkpoint size telemetry: one gauge series per direction plus a
    step-event record, so bench/telemetry logs show how much state each
    save/load moved (ISSUE: memory observability covers disk-bound state
    too, not just HBM). `seconds` is the wall duration of the transfer —
    the goodput ledger prices checkpoint badput from it when the run
    checkpoints through io.py directly rather than multihost."""
    try:
        from . import telemetry
        telemetry.gauge(
            "checkpoint_bytes",
            "tensor payload bytes of the last save_vars/load_vars",
            labels=("op",)).labels(op=op).set(nbytes)
        fields = {"dirname": dirname, "bytes": nbytes, "vars": n_vars}
        if seconds is not None:
            fields["seconds"] = seconds
        telemetry.log_event(f"checkpoint_{op}", **fields)
        from . import tracing
        if tracing.enabled() and seconds is not None:
            t_end = time.monotonic()
            tracing.record_span(
                f"checkpoint_{op}", t_end - seconds, t_end,
                attrs={"dirname": dirname, "bytes": nbytes,
                       "vars": n_vars})
    except Exception:
        pass


def _save_one(path: str, arr: np.ndarray, lod):
    with open(path, "wb") as f:
        pickle.dump({"tensor": arr, "lod": lod, "version": 0}, f)


def _load_one(path: str):
    with open(path, "rb") as f:
        d = pickle.load(f)
    return d["tensor"], d.get("lod")


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter,
              save_file_name=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              save_file_name=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              load_file_name: Optional[str] = None):
    main_program = main_program or default_main_program()
    t0 = time.perf_counter()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    total_bytes = n_loaded = 0
    # cached tables restore into the host-DRAM authoritative slab (the
    # checkpoint holds the FULL table) and invalidate residency — the
    # scope keeps the cache slab, whose slots re-stage on first touch
    emb_cache = getattr(main_program, "_emb_cache", None)

    def _restore(name, arr, lod):
        if emb_cache is not None and emb_cache.load_host(
                name, np.asarray(arr)):
            return
        scope.set_var(name, LoDTensor(arr, lod) if lod else arr)

    if load_file_name is not None:
        with open(os.path.join(dirname, load_file_name), "rb") as f:
            blob = pickle.load(f)
        for v in vars:
            if v.name in blob:
                arr, lod = blob[v.name]
                total_bytes += np.asarray(arr).nbytes
                n_loaded += 1
                _restore(v.name, arr, lod)
        _record_checkpoint("load", dirname, total_bytes, n_loaded,
                           time.perf_counter() - t0)
        return
    for v in vars:
        path = os.path.join(dirname, v.name)
        if not os.path.exists(path):
            continue
        arr, lod = _load_one(path)
        total_bytes += np.asarray(arr).nbytes
        n_loaded += 1
        _restore(v.name, arr, lod)
    _record_checkpoint("load", dirname, total_bytes, n_loaded,
                       time.perf_counter() - t0)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter,
              load_file_name=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              load_file_name=filename)


def _strip_training_ops(program):
    """Drop backward/optimize-role ops before inference pruning (reference
    inference_optimize + OpRole attr, prune.cc:187): without this, a fetch
    var built AFTER minimize() (e.g. a crf_decoding path) sees the
    optimizer's in-place ParamOut as the parameter's producer and the
    reverse prune drags the whole training tail into the inference slice."""
    p = program.clone()
    for b in p.blocks:
        b.desc.ops = [d for d in b.desc.ops
                      if d.attrs.get("op_role") not in ("backward",
                                                        "optimize")]
        b._sync_ops()
    return p


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    forward = _strip_training_ops(main_program)
    pruned = forward.prune([], [t.name for t in target_vars])
    out = pruned.clone(for_test=True)
    emb_cache = getattr(main_program, "_emb_cache", None)
    if emb_cache is not None:     # shares the source scope's cache slabs
        out._emb_cache = emb_cache
    return out


def save_inference_model(dirname: str, feeded_var_names: List[str],
                         target_vars: List[Variable], executor: Executor,
                         main_program: Optional[Program] = None,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """Prune to the inference slice and persist program + params
    (reference io.py:298)."""
    main_program = main_program or default_main_program()
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    os.makedirs(dirname, exist_ok=True)
    pruned = _strip_training_ops(main_program).prune(
        feeded_var_names, [t.name for t in target_vars])
    inference_program = pruned.clone(for_test=True)
    # a hot-row emb cache lives on the PROGRAM but its slabs live in the
    # SCOPE, which the pruned clone shares — without propagating it, the
    # save below would checkpoint the [cache_rows, dim] device slab as if
    # it were the full table (and running the clone would feed global ids
    # into slot-indexed lookups)
    emb_cache = getattr(main_program, "_emb_cache", None)
    if emb_cache is not None:
        inference_program._emb_cache = emb_cache
    # feeds the targets do not depend on were pruned away; drop them from
    # the recorded feed list so inference callers need not supply them
    # (e.g. the label input of a training program)
    from .framework.framework import op_external_reads
    block = inference_program.global_block()
    live = set()
    for op_ in block.ops:
        live |= op_external_reads(inference_program, op_)
    feed_names = [n for n in feeded_var_names if n in live]
    # serving admission starts here: a saved model must never carry the
    # training tail (prune's training-role skip + the strip above make
    # this unreachable unless a grad var was requested as a target)
    leaked = [op_.type for op_ in block.ops
              if op_.desc.attrs.get("op_role") in ("backward", "optimize")
              or op_.type.endswith("_grad")]
    if leaked:
        raise ValueError(
            f"save_inference_model: training-only ops {leaked} survived "
            f"pruning — a target var appears to be a gradient/optimizer "
            f"output, which is not an inference fetch")
    # a gradient target doesn't leak its producer (the strip above removed
    # it) — it leaves an UNCOMPUTABLE fetch instead: no surviving op writes
    # it and it's neither a feed nor a persistable, so the saved model
    # would only fail at first serve compile. Refuse at export time.
    produced = {n for op_ in block.ops for n in op_.output_arg_names}
    for t in target_vars:
        v = block.desc.vars.get(t.name)
        if (t.name not in produced and t.name not in feeded_var_names
                and not (v is not None and v.persistable)):
            what = ("a gradient" if t.name.endswith("@GRAD")
                    or t.name.endswith("_grad") else "not computable")
            raise ValueError(
                f"save_inference_model: target '{t.name}' is {what} — "
                f"its producer was stripped with the training tail, so "
                f"the inference program cannot compute it from the feeds")
    meta = {
        "program": inference_program.to_json(),
        "feed_names": feed_names,
        "fetch_names": [t.name for t in target_vars],
    }
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "wb") as f:
        pickle.dump(meta, f)
    save_persistables(executor, dirname, inference_program,
                      filename=params_filename)
    return inference_program


def load_inference_model(dirname: str, executor: Executor,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """Returns (program, feed_target_names, fetch_targets)
    (reference io.py:383)."""
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        meta = pickle.load(f)
    program = Program.from_json(meta["program"])
    load_persistables(executor, dirname, program, filename=params_filename)
    fetch_targets = [program.global_block().var(n)
                     for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_targets
