"""Causal span tracing for serving requests and training steps.

The metrics registry (telemetry.py) answers "how is the fleet doing in
aggregate"; this module answers "where did *this* request's 40 ms go".
Spans carry a trace id / span id / parent id, wall+monotonic timestamps,
attrs, and point events, and finished spans land in a bounded process-wide
ring buffer that `/spans` (obs_server.py) and the exporters read.

Design points, in the order they matter:

  * **Off by default, cheap when off.** `enabled()` is one attribute
    read; every instrumentation site in executor/io/serving guards on it.
    Enable programmatically with `enable()` or via `PADDLE_TPU_TRACE`
    (``1`` for everything, a float like ``0.1`` for head sampling).
  * **Head sampling at the root.** The keep/drop decision is made once,
    when a root span starts, and inherited by every child — a trace is
    either complete or absent, never a partial tree. The sampler is a
    deterministic error-feedback accumulator (no RNG), so a 0.25 rate
    keeps exactly every 4th trace.
  * **Two span styles.** `span()`/`start_span()` bracket live code with
    thread-local context propagation (children discover their parent from
    the stack). `record_span()` creates a span retroactively from
    timestamps already measured — the executor and batcher time their
    phases anyway, so tracing adds no second clock read on the hot path.
  * **Exports.** `export_chrome_trace()` writes Perfetto-loadable
    ``{"traceEvents": [...]}`` JSON (complete "X" events, µs); JSONL via
    `export_jsonl()` or a live sink (`PADDLE_TPU_TRACE_JSONL`) mirroring
    each finished span as one JSON object per line.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import telemetry

_DEFAULT_CAPACITY = 4096

_LOCK = threading.Lock()
_SPANS: "list" = []          # ring of finished span dicts (bounded)
_CAPACITY = _DEFAULT_CAPACITY
_ENABLED = False
_SAMPLE = 1.0
_SAMPLE_ACC = 0.0            # error-feedback accumulator for head sampling
_JSONL_PATH: Optional[str] = None
_IDS = itertools.count(1)
_LOCAL = threading.local()   # .stack — list of live Span objects

# offset from time.monotonic() to wall-clock, so spans recorded from
# monotonic timestamps can still report a wall "ts"
_WALL_OFFSET = time.time() - time.monotonic()


class Span:
    """One live span. End it with `.end()` (or let the `span()` context
    manager do it); only ended spans reach the ring buffer."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "end_t", "attrs", "events", "sampled")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start: float, sampled: bool,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end_t: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Dict[str, Any]] = []
        self.sampled = sampled

    def set_attr(self, key: str, value: Any) -> "Span":
        if self.sampled:
            self.attrs[key] = value
        return self

    def add_event(self, name: str, **attrs) -> "Span":
        if self.sampled:
            ev = {"name": name, "t": time.monotonic()}
            if attrs:
                ev.update(attrs)
            self.events.append(ev)
        return self

    def end(self, end: Optional[float] = None, **attrs):
        if self.end_t is not None:  # idempotent: first end wins
            return
        self.end_t = time.monotonic() if end is None else end
        if attrs and self.sampled:
            self.attrs.update(attrs)
        _finish(self)

    def to_dict(self) -> Dict[str, Any]:
        end = self.end_t if self.end_t is not None else time.monotonic()
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": end,
            "dur_s": max(end - self.start, 0.0),
            "ts": self.start + _WALL_OFFSET,
            "attrs": self.attrs,
            "events": self.events,
        }


class _NullSpan:
    """Returned when tracing is off or the trace was head-sampled out.
    Accepts the whole Span surface and does nothing; `sampled` stays
    False so children created under it stay null too."""

    __slots__ = ()
    sampled = False
    trace_id = span_id = parent_id = None
    name = ""

    def set_attr(self, key, value):
        return self

    def add_event(self, name, **attrs):
        return self

    def end(self, end=None, **attrs):
        pass

    def to_dict(self):
        return {}


_NULL = _NullSpan()


def _next_id() -> str:
    return f"{os.getpid():x}.{next(_IDS):x}"


def _stack() -> list:
    st = getattr(_LOCAL, "stack", None)
    if st is None:
        st = _LOCAL.stack = []
    return st


def _sample_root() -> bool:
    """Deterministic head sampling: keep when the accumulated rate
    crosses 1.0 (an error-feedback quantizer — exact long-run rate,
    no RNG so traces are reproducible)."""
    global _SAMPLE_ACC
    with _LOCK:
        s = _SAMPLE
        if s >= 1.0:
            return True
        if s <= 0.0:
            return False
        _SAMPLE_ACC += s
        if _SAMPLE_ACC >= 1.0:
            _SAMPLE_ACC -= 1.0
            return True
    return False


def _finish(sp: Span):
    if not sp.sampled:
        return
    d = sp.to_dict()
    with _LOCK:
        _SPANS.append(d)
        dropped = len(_SPANS) - _CAPACITY
        if dropped > 0:
            del _SPANS[:dropped]
            telemetry.counter(
                "trace_spans_dropped_total",
                "finished spans evicted from the bounded ring buffer").inc(
                    dropped)
        path = _JSONL_PATH
    telemetry.counter(
        "trace_spans_total", "finished (sampled) spans, by span name",
        labels=("name",)).labels(name=sp.name).inc()
    if path:
        try:
            with open(path, "a") as f:
                f.write(json.dumps(d) + "\n")
        except OSError:
            pass


# --- lifecycle ---------------------------------------------------------------

def enable(sample: float = 1.0, capacity: Optional[int] = None,
           jsonl: Optional[str] = None):
    """Turn tracing on. `sample` in (0, 1] head-samples root spans;
    `capacity` bounds the finished-span ring; `jsonl` mirrors finished
    spans to a file, one JSON object per line."""
    global _ENABLED, _SAMPLE, _CAPACITY, _JSONL_PATH
    with _LOCK:
        _SAMPLE = min(max(float(sample), 0.0), 1.0)
        if capacity is not None:
            _CAPACITY = max(int(capacity), 1)
        if jsonl is not None:
            _JSONL_PATH = jsonl
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def reset():
    """Drop all recorded spans and restore defaults (tests)."""
    global _SPANS, _ENABLED, _SAMPLE, _SAMPLE_ACC, _CAPACITY, _JSONL_PATH
    with _LOCK:
        _SPANS = []
        _SAMPLE_ACC = 0.0
        _SAMPLE = 1.0
        _CAPACITY = _DEFAULT_CAPACITY
        _JSONL_PATH = None
    _ENABLED = False
    _LOCAL.stack = []


def maybe_enable_from_env():
    """Honor PADDLE_TPU_TRACE: '1'/'true'/'on' → full tracing, a float
    like '0.1' → head sampling at that rate, '0' → leave off.
    PADDLE_TPU_TRACE_JSONL names the live JSONL sink."""
    raw = os.environ.get("PADDLE_TPU_TRACE", "").strip().lower()
    if not raw:
        return
    sample = None
    if raw in ("1", "true", "on", "yes"):
        sample = 1.0
    elif raw in ("0", "false", "off", "no"):
        return
    else:
        try:
            sample = float(raw)
        except ValueError:
            return
    if sample and sample > 0.0:
        enable(sample=sample,
               jsonl=os.environ.get("PADDLE_TPU_TRACE_JSONL") or None)


# --- span creation -----------------------------------------------------------

def current_span():
    """The innermost live span on this thread's context stack (or a null
    span). Lets leaf code attach attrs/events without plumbing handles."""
    st = _stack()
    return st[-1] if st else _NULL


def start_span(name: str, parent=None, attrs: Optional[Dict] = None):
    """Start a span without touching the context stack — for handles
    carried across threads (e.g. a serving request whose children are
    recorded by the batcher worker). Caller must `.end()` it."""
    if not _ENABLED:
        return _NULL
    if parent is None or isinstance(parent, _NullSpan):
        if parent is None:
            st = _stack()
            parent = st[-1] if st else None
    if parent is not None and not parent.sampled:
        return _NULL
    if parent is None:
        if not _sample_root():
            return _NULL
        trace_id = _next_id()
        parent_id = None
    else:
        trace_id = parent.trace_id
        parent_id = parent.span_id
    return Span(name, trace_id, _next_id(), parent_id,
                time.monotonic(), True, attrs)


class _SpanCtx:
    __slots__ = ("name", "attrs", "sp")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs
        self.sp = _NULL

    def __enter__(self):
        self.sp = start_span(self.name, attrs=self.attrs)
        if self.sp.sampled:
            _stack().append(self.sp)
        return self.sp

    def __exit__(self, exc_type, exc, tb):
        if self.sp.sampled:
            st = _stack()
            if st and st[-1] is self.sp:
                st.pop()
            if exc_type is not None:
                self.sp.set_attr("error", f"{exc_type.__name__}: {exc}")
        self.sp.end()
        return False


def span(name: str, **attrs):
    """Context manager: start a span as a child of the current thread
    context, push it, end+pop on exit (recording any exception)."""
    return _SpanCtx(name, attrs or None)


def capture_context():
    """Snapshot this thread's innermost live span as a handle a worker
    thread can `adopt()` — how DoubleBufferedFeeder's builder threads
    parent their prefetch spans under the owning step trace instead of
    minting orphan roots. None (and adopt(None) is a no-op) when nothing
    is live."""
    st = _stack()
    return st[-1] if st else None


class _AdoptCtx:
    __slots__ = ("ctx", "pushed")

    def __init__(self, ctx):
        self.ctx = ctx
        self.pushed = False

    def __enter__(self):
        if self.ctx is not None and getattr(self.ctx, "sampled", False):
            _stack().append(self.ctx)
            self.pushed = True
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        if self.pushed:
            st = _stack()
            if st and st[-1] is self.ctx:
                st.pop()
            elif self.ctx in st:
                st.remove(self.ctx)
        return False


def adopt(ctx):
    """Context manager: make a `capture_context()` handle (taken on
    another thread) this thread's current span, so `span()`/`start_span`
    children recorded here join the owning trace. The adopted span is
    NOT ended on exit — its owner ends it."""
    return _AdoptCtx(ctx)


def record_span(name: str, start: float, end: float, parent=None,
                trace_id: Optional[str] = None,
                attrs: Optional[Dict] = None):
    """Create an already-finished span from monotonic timestamps measured
    by the caller — the retroactive style used by code that times its
    phases anyway (executor steps, batcher phases, checkpoint io).
    Returns the span (its span_id can parent further retro spans)."""
    if not _ENABLED:
        return _NULL
    if parent is not None:
        if not parent.sampled:
            return _NULL
        tid, pid = parent.trace_id, parent.span_id
    elif trace_id is not None:
        tid, pid = trace_id, None
    else:
        if not _sample_root():
            return _NULL
        tid, pid = _next_id(), None
    sp = Span(name, tid, _next_id(), pid, float(start), True, attrs)
    sp.end(end=float(end))
    return sp


# --- read / export -----------------------------------------------------------

def recent_spans(n: Optional[int] = None, name: Optional[str] = None,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Finished spans, oldest first, optionally filtered by span name or
    trace id, optionally the last `n` after filtering."""
    with _LOCK:
        out = list(_SPANS)
    if name is not None:
        out = [s for s in out if s["name"] == name]
    if trace_id is not None:
        out = [s for s in out if s["trace_id"] == trace_id]
    if n is not None:
        out = out[-int(n):]
    return out


def trace_tree(trace_id: str) -> List[Dict[str, Any]]:
    """The spans of one trace as a forest: roots with nested
    "children" lists, children sorted by start time."""
    spans = recent_spans(trace_id=trace_id)
    by_id = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots = []
    for s in by_id.values():
        parent = by_id.get(s["parent_id"]) if s["parent_id"] else None
        (parent["children"] if parent else roots).append(s)
    for s in by_id.values():
        s["children"].sort(key=lambda c: c["start"])
    roots.sort(key=lambda c: c["start"])
    return roots


def export_chrome_trace(path: str,
                        spans: Optional[List[Dict]] = None) -> int:
    """Write spans as chrome-trace / Perfetto JSON (`traceEvents` with
    complete "X" events, microsecond timestamps). Returns the number of
    events written. Load in chrome://tracing or ui.perfetto.dev."""
    spans = recent_spans() if spans is None else spans
    pid = os.getpid()
    # one display row per trace: tid = trace ordinal, labelled via
    # thread_name metadata so request trees stack instead of interleaving
    tids: Dict[str, int] = {}
    events = []
    for s in spans:
        tid = tids.setdefault(s["trace_id"], len(tids) + 1)
        args = {"span_id": s["span_id"], "trace_id": s["trace_id"]}
        args.update(s.get("attrs") or {})
        events.append({
            "name": s["name"], "ph": "X", "pid": pid, "tid": tid,
            "ts": s["start"] * 1e6,
            "dur": max(s["end"] - s["start"], 0.0) * 1e6,
            "cat": "paddle_tpu", "args": args,
        })
    for trace_id, tid in tids.items():
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid,
                       "args": {"name": f"trace {trace_id}"}})
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


def export_jsonl(path: str, spans: Optional[List[Dict]] = None) -> int:
    """Write spans (default: the whole ring) as JSONL; returns count."""
    spans = recent_spans() if spans is None else spans
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    return len(spans)


maybe_enable_from_env()
