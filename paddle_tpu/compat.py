"""Checkpoint compatibility helpers for reference-trained weights.

This framework's fused RNN cells use a different gate layout / update rule
than the reference's CUDA kernels (a deliberate TPU-first choice — the
layouts here match the jnp.split order the `lax.scan` cells use):

  LSTM  (ops/sequence_ops.py): gate order [input, forget, cell, output]
        along the 4H axis; the reference orders [cell, input, forget,
        output] (reference: paddle/fluid/operators/math/lstm_compute.h,
        detail/lstm_cpu_kernel.h).
  GRU   (ops/sequence_ops.py): weight [H, 3H] = [update, reset | candidate]
        and h = u * h_prev + (1 - u) * c; the reference computes
        h = u * c + (1 - u) * h_prev (reference: operators/math/gru_compute.h,
        detail/gru_cpu_kernel.h — i.e. the roles of u and (1-u) are swapped).

Training from scratch is unaffected (the cells are self-consistent and
grad-checked). Porting reference-trained weights requires the converters
below. The GRU converter is an involution (applying twice returns the
original); the LSTM converters are a permutation and its inverse — use the
`_to_reference` variant to go back.
"""

from __future__ import annotations

import numpy as np

__all__ = ["convert_lstm_weight_from_reference",
           "convert_lstm_weight_to_reference",
           "convert_gru_weight_from_reference"]


def _split4(w, axis):
    return np.split(np.asarray(w), 4, axis=axis)


def convert_lstm_weight_from_reference(weight, axis=-1):
    """Reorder a reference LSTM gate weight/bias from the reference's
    [cell, input, forget, output] layout into this framework's
    [input, forget, cell, output] layout along `axis` (the 4H axis)."""
    c, i, f, o = _split4(weight, axis)
    return np.concatenate([i, f, c, o], axis=axis)


def convert_lstm_weight_to_reference(weight, axis=-1):
    """Inverse of convert_lstm_weight_from_reference: reorder
    [input, forget, cell, output] back to the reference's
    [cell, input, forget, output] layout."""
    i, f, c, o = _split4(weight, axis)
    return np.concatenate([c, i, f, o], axis=axis)


def convert_gru_weight_from_reference(gate_weight, axis=-1):
    """Swap the update/reset blocks of a reference GRU gate weight/bias
    ([update, reset] along 2H) to account for the inverted update rule:
    reference h = u*c + (1-u)*h_prev equals ours with u' = 1 - u, which for
    sigmoid gates means negating the update-gate pre-activation — not a
    pure permutation. For *weights* the equivalent transform is to negate
    the update-gate block (weight AND bias); candidate block is unchanged.

    Pass the full [D, 3H] weight (or [3H] bias); returns a copy with the
    update-gate third negated.
    """
    w = np.array(gate_weight, copy=True)
    h3 = w.shape[axis]
    assert h3 % 3 == 0, "expected a [.., 3H] GRU gate weight"
    h = h3 // 3
    sl = [slice(None)] * w.ndim
    sl[axis] = slice(0, h)
    w[tuple(sl)] = -w[tuple(sl)]
    return w
