"""Typed error hierarchy (reference: paddle/fluid/platform/enforce.h
EnforceNotMet + pybind/exception.cc mapping C++ exceptions onto Python
types). The executor raises EnforceNotMet for op execution failures — it
carries the failing operator, its declared inputs/outputs, the live input
shapes, and the op's Python creation site (CustomStackTrace parity,
reference paddle/utils/CustomStackTrace.h layer-stack dump)."""

from __future__ import annotations

__all__ = ["EnforceNotMet", "EOFException", "NonFiniteError", "NotFoundError",
           "OOMError", "ProgramVerifyError", "ServingOverloadError"]


class EnforceNotMet(RuntimeError):
    """An operator's runtime contract failed (reference PADDLE_ENFORCE)."""

    def __init__(self, message, op_type=None, creation_site=None):
        super().__init__(message)
        self.op_type = op_type
        self.creation_site = creation_site


class NonFiniteError(FloatingPointError, RuntimeError):
    """A NaN/Inf was detected in a tensor (reference FLAGS_check_nan_inf,
    executor.cc:325 CheckTensorNANOrInf). Subclasses both FloatingPointError
    (the eager per-op check's historical type) and RuntimeError (the jit
    fetch-level check's), so existing handlers keep working.

    Structured fields localize the origin: `var_name`/`dtype` name the tensor
    the detection fired on, `op_type`/`op_index` the producing op when known,
    `stats` its inspector.TensorStats, and `attribution` the full
    inspector.Attribution from the bisection re-run (None when attribution is
    disabled or inconclusive)."""

    def __init__(self, message, var_name=None, dtype=None, op_type=None,
                 op_index=None, stats=None, attribution=None,
                 feed_signature=None):
        super().__init__(message)
        self.var_name = var_name
        self.dtype = dtype
        self.op_type = op_type
        self.op_index = op_index
        self.stats = stats
        self.attribution = attribution
        self.feed_signature = feed_signature

    def to_dict(self):
        """JSON-serializable view (flight-recorder crash reports)."""
        return {
            "type": type(self).__name__,
            "message": str(self),
            "var_name": self.var_name,
            "dtype": self.dtype,
            "op_type": self.op_type,
            "op_index": self.op_index,
            "stats": self.stats.to_dict() if self.stats is not None else None,
            "attribution": (self.attribution.to_dict()
                            if self.attribution is not None else None),
            "feed_signature": ([list(s) for s in self.feed_signature]
                               if self.feed_signature else None),
        }


class OOMError(MemoryError, RuntimeError):
    """The device ran out of HBM (XLA RESOURCE_EXHAUSTED). jax surfaces
    this as a bare XlaRuntimeError whose message names the failed
    allocation but nothing about WHAT is occupying the chip; the executor
    (memory.maybe_oom_error) replaces it with this structured error.
    Subclasses MemoryError (the natural Python type) and RuntimeError (so
    handlers catching the raw jax error's base type keep working); the
    message retains the RESOURCE_EXHAUSTED marker for text-matching retry
    loops.

    Fields: `breakdown` maps byte classes (params/opt_state/feeds plus
    device bytes_in_use/bytes_limit when memory_stats is available),
    `top_buffers` lists the largest live arrays (named when they map back
    to scope/feed vars), `donation_lost_bytes` counts donated state XLA
    failed to alias in place, `analysis` is the block's static
    memory.ProgramMemory view, and `suggestions` are concrete next steps
    (donate, AMP, remat, what-if batch sizing)."""

    def __init__(self, message, program=None, breakdown=None,
                 top_buffers=None, donation_lost_bytes=0, analysis=None,
                 suggestions=None, device=None):
        super().__init__(message)
        self.program = program
        self.breakdown = dict(breakdown or {})
        self.top_buffers = list(top_buffers or [])
        self.donation_lost_bytes = donation_lost_bytes
        self.analysis = analysis
        self.suggestions = list(suggestions or [])
        self.device = device

    def to_dict(self):
        """JSON-serializable view (flight-recorder crash reports)."""
        return {
            "type": type(self).__name__,
            "message": str(self),
            "program": self.program,
            "breakdown": self.breakdown,
            "top_buffers": self.top_buffers,
            "donation_lost_bytes": self.donation_lost_bytes,
            "analysis": self.analysis,
            "suggestions": self.suggestions,
            "device": self.device,
        }


class ProgramVerifyError(RuntimeError):
    """The static analyzer (paddle_tpu.analysis) found error-severity
    diagnostics in a program about to compile. Raised by the executor
    under PADDLE_TPU_VERIFY=1 *before* tracing, so the message points at
    the op's Python creation site instead of a JAX traceback — the
    compile-time InferShape story of the reference, restored.

    `diagnostics` holds the analysis.Diagnostic objects (error severity
    only); the message numbers them with op index, source site and hint."""

    def __init__(self, diagnostics, program_name=None):
        self.diagnostics = list(diagnostics)
        self.program_name = program_name
        head = (f"program verification failed: "
                f"{len(self.diagnostics)} error(s)")
        if program_name:
            head += f" in {program_name}"
        body = "\n".join(f"  [{i + 1}] {d.format()}"
                         for i, d in enumerate(self.diagnostics))
        super().__init__(head + ("\n" + body if body else "") +
                         "\n(set PADDLE_TPU_VERIFY=0 to skip verification, "
                         "or run `python -m paddle_tpu analyze` for the "
                         "full report)")

    def to_dict(self):
        """JSON-serializable view (flight-recorder crash reports)."""
        return {
            "type": type(self).__name__,
            "message": str(self),
            "program_name": self.program_name,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }


class NotFoundError(KeyError):
    """A variable/operator lookup failed (reference NotFound error code)."""


class ServingOverloadError(RuntimeError):
    """A serving request was rejected by overload control (serving/batcher):
    either the bounded request queue was full at submit time, or the
    request's deadline expired before its batch reached the device.
    Shedding with a typed error keeps the accepted requests' latency bounded
    instead of letting the queue collapse under 2x load — the caller is
    expected to retry against another replica or surface the rejection.

    `reason` is the shed cause ("queue_full" | "deadline" | "shutdown"),
    `queue_depth` the depth observed at rejection."""

    def __init__(self, message, reason=None, queue_depth=None):
        super().__init__(message)
        self.reason = reason
        self.queue_depth = queue_depth

    def to_dict(self):
        """JSON-serializable view (flight-recorder crash reports)."""
        return {
            "type": type(self).__name__,
            "message": str(self),
            "reason": self.reason,
            "queue_depth": self.queue_depth,
        }


def __getattr__(name):
    # canonical home of EOFException is layers.io (it predates this
    # module); lazily re-exported so the typed hierarchy is one import
    # away without an import cycle
    if name == "EOFException":
        from .layers.io import EOFException
        return EOFException
    raise AttributeError(name)
