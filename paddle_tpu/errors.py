"""Typed error hierarchy (reference: paddle/fluid/platform/enforce.h
EnforceNotMet + pybind/exception.cc mapping C++ exceptions onto Python
types). The executor raises EnforceNotMet for op execution failures — it
carries the failing operator, its declared inputs/outputs, the live input
shapes, and the op's Python creation site (CustomStackTrace parity,
reference paddle/utils/CustomStackTrace.h layer-stack dump)."""

from __future__ import annotations

__all__ = ["EnforceNotMet", "EOFException", "NotFoundError"]


class EnforceNotMet(RuntimeError):
    """An operator's runtime contract failed (reference PADDLE_ENFORCE)."""

    def __init__(self, message, op_type=None, creation_site=None):
        super().__init__(message)
        self.op_type = op_type
        self.creation_site = creation_site


class NotFoundError(KeyError):
    """A variable/operator lookup failed (reference NotFound error code)."""


def __getattr__(name):
    # canonical home of EOFException is layers.io (it predates this
    # module); lazily re-exported so the typed hierarchy is one import
    # away without an import cycle
    if name == "EOFException":
        from .layers.io import EOFException
        return EOFException
    raise AttributeError(name)
