"""WMT-14 fr-en (reference: python/paddle/v2/dataset/wmt14.py, used by the
machine_translation book chapter). Schema: (src_ids, trg_ids_with_<s>,
trg_ids_next_with_<e>) variable-length int64 sequences. Synthetic
surrogate: target = elementwise function of source, so seq2seq+attention
can learn it."""

from __future__ import annotations

import numpy as np

_START, _END, _UNK = 0, 1, 2


def _default_dict(size):
    d = {"<s>": _START, "<e>": _END, "<unk>": _UNK}
    for i in range(3, size):
        d[f"w{i}"] = i
    return d


_TRAIN_N, _TEST_N = 2048, 256


def _reader(n, dict_size, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, ln).tolist()
            # target = deterministic chain seeded by the source head: the
            # step-to-step rule is learnable via teacher forcing, the seed
            # via the encoder/attention path
            trg = [(src[0] * 3 + 1) % (dict_size - 3) + 3]
            for _k in range(ln - 1):
                trg.append((trg[-1] * 5 + 7) % (dict_size - 3) + 3)
            yield src, [_START] + trg, trg + [_END]
    return reader


def train(dict_size):
    return _reader(_TRAIN_N, dict_size, 0)


def test(dict_size):
    return _reader(_TEST_N, dict_size, 1)


def get_dict(dict_size, reverse=False):
    src = _default_dict(dict_size)
    trg = _default_dict(dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
