"""WMT-14 fr-en (reference: python/paddle/v2/dataset/wmt14.py, used by the
machine_translation book chapter). Schema: (src_ids, trg_ids_with_<s>,
trg_ids_next_with_<e>) variable-length int64 sequences.

Real data: drop `wmt14.tgz` (the reference's shrunk training tarball,
wmt14.py:40-42: members train/train, test/test plus src.dict/trg.dict)
under DATA_HOME/wmt14/ and train/test/get_dict parse it exactly as the
reference (wmt14.py:53-110): first dict_size dict lines become ids,
tab-separated parallel lines, source wrapped <s>...<e>, UNK id 2, pairs
longer than 80 tokens dropped, target emitted as (<s>+ids, ids+<e>).
Synthetic surrogate otherwise: target = deterministic function of source,
so seq2seq+attention can learn it."""

from __future__ import annotations

import tarfile

import numpy as np

from . import common

_START, _END, _UNK = 0, 1, 2
START, END, UNK = "<s>", "<e>", "<unk>"
UNK_IDX = _UNK

_TRAIN_N, _TEST_N = 2048, 256
_FILE = "wmt14.tgz"


def _have_real():
    return common.have_real_data("wmt14", _FILE)


def _default_dict(size):
    d = {START: _START, END: _END, UNK: _UNK}
    for i in range(3, size):
        d[f"w{i}"] = i
    return d


def _read_to_dict(dict_size):
    """First dict_size lines of the tarball's src.dict/trg.dict members
    (reference wmt14.py:53-76)."""
    def to_dict(fd, size):
        out = {}
        for line_count, line in enumerate(fd):
            if line_count >= size:
                break
            out[line.decode("utf-8", errors="ignore").strip()] = line_count
        return out

    with tarfile.open(common.cache_path("wmt14", _FILE)) as f:
        src_name, = [m.name for m in f if m.name.endswith("src.dict")]
        src_dict = to_dict(f.extractfile(src_name), dict_size)
        trg_name, = [m.name for m in f if m.name.endswith("trg.dict")]
        trg_dict = to_dict(f.extractfile(trg_name), dict_size)
    return src_dict, trg_dict


def _real_reader(file_name, dict_size):
    # parse the dicts once per reader construction, not once per epoch
    src_dict, trg_dict = _read_to_dict(dict_size)

    def reader():
        with tarfile.open(common.cache_path("wmt14", _FILE)) as f:
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    line = line.decode("utf-8", errors="ignore")
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [src_dict.get(w, _UNK)
                               for w in [START] + src_words + [END]]
                    trg_words = parts[1].split()
                    trg_ids = [trg_dict.get(w, _UNK) for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    trg_ids_next = trg_ids + [trg_dict[END]]
                    trg_ids = [trg_dict[START]] + trg_ids
                    yield src_ids, trg_ids, trg_ids_next
    return reader


def _synthetic_reader(n, dict_size, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, ln).tolist()
            # target = deterministic chain seeded by the source head: the
            # step-to-step rule is learnable via teacher forcing, the seed
            # via the encoder/attention path
            trg = [(src[0] * 3 + 1) % (dict_size - 3) + 3]
            for _k in range(ln - 1):
                trg.append((trg[-1] * 5 + 7) % (dict_size - 3) + 3)
            yield src, [_START] + trg, trg + [_END]
    return reader


def train(dict_size):
    if _have_real():
        return _real_reader("train/train", dict_size)
    return _synthetic_reader(_TRAIN_N, dict_size, 0)


def test(dict_size):
    if _have_real():
        return _real_reader("test/test", dict_size)
    return _synthetic_reader(_TEST_N, dict_size, 1)


def get_dict(dict_size, reverse=False):
    if _have_real():
        src, trg = _read_to_dict(dict_size)
    else:
        src, trg = _default_dict(dict_size), _default_dict(dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
