"""CoNLL-2005 SRL (reference: python/paddle/v2/dataset/conll05.py, used by
the label_semantic_roles book chapter). Schema per sample: 8 parallel
variable-length int64 sequences (word, predicate, ctx_n2..ctx_p2, mark)
plus the IOB label sequence. Synthetic surrogate ties labels to word ids."""

from __future__ import annotations

import numpy as np

WORD_VOCAB = 44068
PRED_VOCAB = 3162
MARK_VOCAB = 2
LABEL_N = 59

_TRAIN_N, _TEST_N = 1024, 128


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(PRED_VOCAB)}
    label_dict = {f"l{i}": i for i in range(LABEL_N)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    raise RuntimeError("pretrained emb unavailable without egress; "
                       "initialize embeddings randomly instead")


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(4, 20))
            words = rng.randint(0, 2000, ln)
            pred_id = int(rng.randint(0, PRED_VOCAB))
            pred = np.full(ln, pred_id)
            ctxs = [np.roll(words, k) for k in (-2, -1, 0, 1, 2)]
            mark = (rng.rand(ln) < 0.2).astype(np.int64)
            labels = (words * 7 + pred_id) % LABEL_N  # learnable mapping
            yield (words.tolist(), pred.tolist(),
                   ctxs[0].tolist(), ctxs[1].tolist(), ctxs[2].tolist(),
                   ctxs[3].tolist(), ctxs[4].tolist(), mark.tolist(),
                   labels.tolist())
    return reader


def test():
    return _reader(_TEST_N, 1)


def train():
    return _reader(_TRAIN_N, 0)
