"""CoNLL-2005 SRL (reference: python/paddle/v2/dataset/conll05.py, used by
the label_semantic_roles book chapter). Schema per sample: 8 parallel
variable-length int64 sequences (word, predicate, ctx_n2..ctx_p2, mark)
plus the IOB label sequence.

Real data: drop `conll05st-tests.tar.gz` plus `wordDict.txt`,
`verbDict.txt`, `targetDict.txt` (reference conll05.py:30-40) under
DATA_HOME/conll05st/ and test() parses the real corpus exactly as the
reference (conll05.py:74-198): the tarball's words.gz/props.gz member
pair, bracket-notation props converted to per-predicate IOB sequences,
context words around the B-V predicate, 2-word mark window. Synthetic
surrogate otherwise (labels tied to word ids so the task is learnable)."""

from __future__ import annotations

import gzip
import tarfile

import numpy as np

from . import common

WORD_VOCAB = 44068
PRED_VOCAB = 3162
MARK_VOCAB = 2
LABEL_N = 59

_TRAIN_N, _TEST_N = 1024, 128

_MODULE = "conll05st"
_DATA_FILE = "conll05st-tests.tar.gz"
_WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"
UNK_IDX = 0


def _have_real():
    return all(common.have_real_data(_MODULE, f) for f in
               (_DATA_FILE, "wordDict.txt", "verbDict.txt",
                "targetDict.txt"))


def load_dict(filename):
    """One token per line -> zero-based ids (reference conll05.py:66-71)."""
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def load_label_dict(filename):
    """B-/I- tag pairs from the target dict then 'O' last (reference
    conll05.py:45-62; sorted for determinism where the reference relied
    on set iteration order)."""
    tag_dict = set()
    with open(filename) as f:
        for line in f:
            line = line.strip()
            if line.startswith("B-") or line.startswith("I-"):
                tag_dict.add(line[2:])
    d = {}
    index = 0
    for tag in sorted(tag_dict):
        d["B-" + tag] = index
        index += 1
        d["I-" + tag] = index
        index += 1
    d["O"] = index
    return d


def _corpus_reader():
    """(sentence words, predicate, IOB labels) triples from the real
    corpus: props columns are per-predicate bracket tag streams
    ('(A0*', '*', '*)' ...) converted to B-/I-/O (conll05.py:74-143)."""
    data_path = common.cache_path(_MODULE, _DATA_FILE)

    def lines(fobj):
        with gzip.GzipFile(fileobj=fobj) as g:
            for raw in g:
                yield raw.decode("utf-8", errors="ignore")

    with tarfile.open(data_path) as tf:
        words_file = lines(tf.extractfile(_WORDS_NAME))
        props_file = lines(tf.extractfile(_PROPS_NAME))
        sentences, one_seg = [], []
        for word, prop in zip(words_file, props_file):
            word = word.strip()
            label = prop.strip().split()
            if len(label) == 0:          # end of sentence
                labels = [[x[i] for x in one_seg]
                          for i in range(len(one_seg[0]))] if one_seg else []
                if len(labels) >= 1:
                    verb_list = [x for x in labels[0] if x != "-"]
                    for i, lbl in enumerate(labels[1:]):
                        cur_tag, in_bracket, lbl_seq = "O", False, []
                        for tok in lbl:
                            if tok == "*" and not in_bracket:
                                lbl_seq.append("O")
                            elif tok == "*" and in_bracket:
                                lbl_seq.append("I-" + cur_tag)
                            elif tok == "*)":
                                lbl_seq.append("I-" + cur_tag)
                                in_bracket = False
                            elif "(" in tok and ")" in tok:
                                cur_tag = tok[1:tok.find("*")]
                                lbl_seq.append("B-" + cur_tag)
                                in_bracket = False
                            elif "(" in tok:
                                cur_tag = tok[1:tok.find("*")]
                                lbl_seq.append("B-" + cur_tag)
                                in_bracket = True
                            else:
                                raise RuntimeError(
                                    f"Unexpected label: {tok}")
                        yield sentences, verb_list[i], lbl_seq
                sentences, one_seg = [], []
            else:
                sentences.append(word)
                one_seg.append(label)


def _real_reader(word_dict, predicate_dict, label_dict):
    """Map the corpus triples to the 9 id sequences (conll05.py:146-198),
    emitted in this module's (word, pred, ctx_n2..ctx_p2, mark, label)
    order."""
    def reader():
        for sentence, predicate, labels in _corpus_reader():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * len(labels)
            if verb_index > 0:
                mark[verb_index - 1] = 1
                ctx_n1 = sentence[verb_index - 1]
            else:
                ctx_n1 = "bos"
            if verb_index > 1:
                mark[verb_index - 2] = 1
                ctx_n2 = sentence[verb_index - 2]
            else:
                ctx_n2 = "bos"
            mark[verb_index] = 1
            ctx_0 = sentence[verb_index]
            if verb_index < len(labels) - 1:
                mark[verb_index + 1] = 1
                ctx_p1 = sentence[verb_index + 1]
            else:
                ctx_p1 = "eos"
            if verb_index < len(labels) - 2:
                mark[verb_index + 2] = 1
                ctx_p2 = sentence[verb_index + 2]
            else:
                ctx_p2 = "eos"

            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            pred_idx = [predicate_dict.get(predicate)] * sen_len
            ctxs = [[word_dict.get(c, UNK_IDX)] * sen_len
                    for c in (ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2)]
            label_idx = [label_dict.get(w) for w in labels]
            yield (word_idx, pred_idx, ctxs[0], ctxs[1], ctxs[2], ctxs[3],
                   ctxs[4], mark, label_idx)
    return reader


def get_dict():
    if _have_real():
        return (load_dict(common.cache_path(_MODULE, "wordDict.txt")),
                load_dict(common.cache_path(_MODULE, "verbDict.txt")),
                load_label_dict(common.cache_path(_MODULE,
                                                  "targetDict.txt")))
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(PRED_VOCAB)}
    label_dict = {f"l{i}": i for i in range(LABEL_N)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    if common.have_real_data(_MODULE, "emb"):
        return np.loadtxt(common.cache_path(_MODULE, "emb"),
                          dtype=np.float32)
    raise RuntimeError("pretrained emb unavailable without egress; "
                       "initialize embeddings randomly instead")


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(4, 20))
            words = rng.randint(0, 2000, ln)
            pred_id = int(rng.randint(0, PRED_VOCAB))
            pred = np.full(ln, pred_id)
            ctxs = [np.roll(words, k) for k in (-2, -1, 0, 1, 2)]
            mark = (rng.rand(ln) < 0.2).astype(np.int64)
            labels = (words * 7 + pred_id) % LABEL_N  # learnable mapping
            yield (words.tolist(), pred.tolist(),
                   ctxs[0].tolist(), ctxs[1].tolist(), ctxs[2].tolist(),
                   ctxs[3].tolist(), ctxs[4].tolist(), mark.tolist(),
                   labels.tolist())
    return reader


def test():
    if _have_real():
        return _real_reader(*get_dict())
    return _synthetic_reader(_TEST_N, 1)


def train():
    # Conll05 train data is not freely available (reference conll05.py:17
    # ships only the public test split); the real-data path serves the
    # test corpus for both, as the reference demo does.
    if _have_real():
        return _real_reader(*get_dict())
    return _synthetic_reader(_TRAIN_N, 0)
