"""UCI housing dataset (reference: python/paddle/v2/dataset/uci_housing.py).

Schema: 13 float32 features (normalized), 1 float32 target. With no cached
real data, serves a deterministic synthetic linear-ish task of the same
shape so fit_a_line trains and converges."""

from __future__ import annotations

import numpy as np

from . import common

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

_TRAIN_N, _TEST_N = 404, 102


def _synthetic():
    rng = np.random.RandomState(42)
    n = _TRAIN_N + _TEST_N
    x = rng.randn(n, 13).astype(np.float32)
    w = rng.randn(13, 1).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n, 1)).astype(np.float32)
    return x, y


def _load():
    path = None
    if common.have_real_data("uci_housing", "housing.data"):
        raw = np.loadtxt(common.cache_path("uci_housing", "housing.data"))
        feats = raw[:, :13]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
        return feats.astype(np.float32), raw[:, 13:14].astype(np.float32)
    return _synthetic()


def train():
    def reader():
        x, y = _load()
        for i in range(_TRAIN_N):
            yield x[i], y[i]
    return reader


def test():
    def reader():
        x, y = _load()
        for i in range(_TRAIN_N, len(x)):
            yield x[i], y[i]
    return reader
