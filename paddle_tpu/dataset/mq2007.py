"""MQ2007 learning-to-rank dataset (reference:
python/paddle/v2/dataset/mq2007.py — LETOR 4.0 query-document pairs, 46
dense features + graded relevance, served in pointwise / pairwise /
listwise forms). Synthetic surrogate with the real schema: per-query
document groups whose relevance correlates with a planted weight vector,
so ranking models have real signal to learn. Real LETOR text files dropped
under DATA_HOME/mq2007/ (train.txt/test.txt) are parsed instead."""

from __future__ import annotations

import functools
import os

import numpy as np

from . import common

FEATURE_DIM = 46          # LETOR 4.0 feature count
_TRAIN_QUERIES, _TEST_QUERIES = 200, 40
_DOCS_PER_QUERY = (8, 20)


def _parse_letor(path):
    """Parse LETOR text lines: `<rel> qid:<id> 1:<v> 2:<v> ... # comment`
    into {qid: [(rel, feature_vector), ...]} (same grammar the reference's
    Query._parse_ accepts)."""
    groups = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(float(parts[0]))
            qid = parts[1].split(":")[1]
            feats = np.full(FEATURE_DIM, -1.0, np.float32)
            for tok in parts[2:]:
                idx, val = tok.split(":")
                i = int(idx) - 1
                if 0 <= i < FEATURE_DIM:
                    feats[i] = float(val)
            groups.setdefault(qid, []).append((rel, feats))
    return groups


def _synthetic_groups(n_queries, seed):
    """Graded relevance planted on a fixed weight vector + noise."""
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(42).randn(FEATURE_DIM).astype(np.float32)
    groups = {}
    for q in range(n_queries):
        n_docs = int(rng.randint(*_DOCS_PER_QUERY))
        feats = rng.randn(n_docs, FEATURE_DIM).astype(np.float32)
        scores = feats @ w + 0.5 * rng.randn(n_docs).astype(np.float32)
        # grade into 0/1/2 by within-query quantile (LETOR-style grades)
        q1, q2 = np.quantile(scores, [0.5, 0.85])
        rels = (scores > q1).astype(int) + (scores > q2).astype(int)
        groups[str(q)] = [(int(r), f) for r, f in zip(rels, feats)]
    return groups


def _load(split, seed):
    fname = f"{split}.txt"
    if common.have_real_data("mq2007", fname):
        return _parse_letor(os.path.join(common.DATA_HOME, "mq2007", fname))
    n = _TRAIN_QUERIES if split == "train" else _TEST_QUERIES
    return _synthetic_groups(n, seed)


def __reader__(split, seed, format="pairwise"):
    def reader():
        groups = _load(split, seed)
        for qid in sorted(groups, key=str):
            docs = [d for d in groups[qid]]
            if sum(r for r, _ in docs) == 0:
                continue              # reference query_filter: drop all-0
            if format == "pointwise":
                for rel, f in docs:
                    yield f, float(rel)
            elif format == "pairwise":
                # all (more-relevant, less-relevant) feature pairs
                for i, (ri, fi) in enumerate(docs):
                    for rj, fj in docs[i + 1:]:
                        if ri > rj:
                            yield 1.0, fi, fj
                        elif rj > ri:
                            yield 1.0, fj, fi
            elif format == "listwise":
                rels = np.array([r for r, _ in docs], np.float32)
                feats = np.stack([f for _, f in docs])
                yield rels, feats
            else:
                raise ValueError(f"unknown format {format!r}")
    return reader


def train(format="pairwise"):
    return __reader__("train", 0, format=format)


def test(format="pairwise"):
    return __reader__("test", 1, format=format)


fetch = functools.partial(common.download,
                          "https://example.invalid/MQ2007.rar", "mq2007")
