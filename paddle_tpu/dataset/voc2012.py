"""Pascal VOC2012 segmentation (reference:
python/paddle/v2/dataset/voc2012.py). Schema: (image [3,H,W] float32 in
[0,1], segmentation mask [H,W] int32 with 21 classes + 255 void).

Real data: drop `VOCtrainval_11-May-2012.tar` (reference voc2012.py:31-37)
under DATA_HOME/voc2012/ and train/test/val parse it as the reference
(voc2012.py:42-79): ImageSets/Segmentation/{trainval,train,val}.txt list
the ids, JPEGImages/{id}.jpg is the image, SegmentationClass/{id}.png is
the palette-indexed mask (np.array of the 'P'-mode PIL image = class
ids). The reference yields HWC uint8; this stack's segmentation contract
is CHW float32 [0,1] + int32 mask, so the real path converts. Synthetic
surrogate otherwise: class-colored rectangles at 64x64."""

from __future__ import annotations

import io
import tarfile

import numpy as np

from . import common

CLASS_NUM = 21          # 20 object classes + background
_TRAIN_N, _TEST_N, _VALID_N = 256, 64, 64
_H = _W = 64

_FILE = "VOCtrainval_11-May-2012.tar"
_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _have_real():
    return common.have_real_data("voc2012", _FILE)


def _real_reader(sub_name):
    """Reference voc2012.py:42-66 with the split-name mapping preserved:
    its train() reads 'trainval', test() reads 'train', val() reads
    'val'."""
    from PIL import Image

    def reader():
        with tarfile.open(common.cache_path("voc2012", _FILE)) as tf:
            names = {m.name: m for m in tf.getmembers()}
            sets = tf.extractfile(names[_SET_FILE.format(sub_name)])
            for line in sets:
                line = line.decode("utf-8").strip()
                if not line:
                    continue
                data = tf.extractfile(names[_DATA_FILE.format(line)]).read()
                label = tf.extractfile(
                    names[_LABEL_FILE.format(line)]).read()
                img = np.asarray(
                    Image.open(io.BytesIO(data)).convert("RGB"),
                    np.float32) / 255.0
                mask = np.asarray(Image.open(io.BytesIO(label)),
                                  np.int32)
                yield img.transpose(2, 0, 1), mask
    return reader


def _sample(rng):
    img = rng.rand(3, _H, _W).astype(np.float32) * 0.2
    mask = np.zeros((_H, _W), np.int32)
    for _ in range(int(rng.randint(1, 4))):
        c = int(rng.randint(1, CLASS_NUM))
        h, w = int(rng.randint(8, 32)), int(rng.randint(8, 32))
        r0 = int(rng.randint(0, _H - h))
        c0 = int(rng.randint(0, _W - w))
        mask[r0:r0 + h, c0:c0 + w] = c
        img[c % 3, r0:r0 + h, c0:c0 + w] += 0.5 + 0.02 * c
    return np.clip(img, 0, 1), mask


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield _sample(rng)
    return reader


def train():
    if _have_real():
        return _real_reader("trainval")
    return _synthetic_reader(_TRAIN_N, 0)


def test():
    if _have_real():
        return _real_reader("train")
    return _synthetic_reader(_TEST_N, 1)


def val():
    if _have_real():
        return _real_reader("val")
    return _synthetic_reader(_VALID_N, 2)
