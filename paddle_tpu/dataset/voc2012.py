"""Pascal VOC2012 segmentation (reference:
python/paddle/v2/dataset/voc2012.py). Schema: (image [3,H,W] float32,
segmentation mask [H,W] int32 with 21 classes). Synthetic surrogate:
rectangles of class-colored regions on a background, 64x64 so the suite
stays light while keeping the (image, dense-mask) contract."""

from __future__ import annotations

import numpy as np

CLASS_NUM = 21          # 20 object classes + background
_TRAIN_N, _TEST_N, _VALID_N = 256, 64, 64
_H = _W = 64


def _sample(rng):
    img = rng.rand(3, _H, _W).astype(np.float32) * 0.2
    mask = np.zeros((_H, _W), np.int32)
    for _ in range(int(rng.randint(1, 4))):
        c = int(rng.randint(1, CLASS_NUM))
        h, w = int(rng.randint(8, 32)), int(rng.randint(8, 32))
        r0 = int(rng.randint(0, _H - h))
        c0 = int(rng.randint(0, _W - w))
        mask[r0:r0 + h, c0:c0 + w] = c
        img[c % 3, r0:r0 + h, c0:c0 + w] += 0.5 + 0.02 * c
    return np.clip(img, 0, 1), mask


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield _sample(rng)
    return reader


def train():
    return _reader(_TRAIN_N, 0)


def test():
    return _reader(_TEST_N, 1)


def val():
    return _reader(_VALID_N, 2)
