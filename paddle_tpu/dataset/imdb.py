"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py). Schema:
variable-length int64 word-id sequences + binary label. Synthetic
surrogate: two disjoint vocab regions by sentiment."""

from __future__ import annotations

import numpy as np

_VOCAB = 5147  # reference word_dict size ballpark
_TRAIN_N, _TEST_N = 2048, 256


def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            ln = int(rng.randint(8, 60))
            lo = 2 + label * (_VOCAB // 2)
            hi = lo + _VOCAB // 2 - 2
            words = rng.randint(lo, hi, ln).tolist()
            yield words, label
    return reader


def train(word_idx=None):
    return _reader(_TRAIN_N, 0)


def test(word_idx=None):
    return _reader(_TEST_N, 1)
