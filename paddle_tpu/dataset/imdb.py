"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py). Schema:
variable-length int64 word-id sequences + binary label (pos=0, neg=1).

Real data: drop `aclImdb_v1.tar.gz` (the Stanford aclImdb tarball,
reference imdb.py:31) under DATA_HOME/imdb/ and tokenize/build_dict/
train/test work exactly as the reference (imdb.py:35-124): sequential tar
scan, punctuation stripped, lowercased whitespace split, frequency-sorted
dict with '<unk>' last, cutoff 150. Synthetic surrogate otherwise: two
disjoint vocab regions by sentiment."""

from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from . import common

_VOCAB = 5147  # reference word_dict size ballpark
_TRAIN_N, _TEST_N = 2048, 256
_FILE = "aclImdb_v1.tar.gz"

_PUNCT = str.maketrans("", "", string.punctuation)


def _have_real():
    return common.have_real_data("imdb", _FILE)


def tokenize(pattern):
    """Sequential scan of the tarball (reference imdb.py:35-52: tarfile
    .next(), not random access), yielding the token list of each member
    whose name matches `pattern`."""
    with tarfile.open(common.cache_path("imdb", _FILE)) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(_PUNCT).lower().split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    """Frequency-sorted word dict over the matching corpus files, words
    with freq <= cutoff dropped, '<unk>' appended last (imdb.py:55-72)."""
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] += 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words = [w for w, _ in dictionary]
    word_idx = dict(zip(words, range(len(words))))
    word_idx["<unk>"] = len(words)
    return word_idx


_DICT_CACHE = None  # building it scans the whole 100k-doc tarball


def word_dict():
    global _DICT_CACHE
    if _have_real():
        if _DICT_CACHE is None:
            _DICT_CACHE = build_dict(
                re.compile(
                    r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
                150)
        return _DICT_CACHE
    return {f"w{i}": i for i in range(_VOCAB)}


def _real_reader(pos_pattern, neg_pattern, word_idx):
    """pos label 0, neg label 1, exactly the reference's assignment
    (imdb.py:75-90)."""
    unk = word_idx["<unk>"]

    def reader():
        for doc in tokenize(pos_pattern):
            yield [word_idx.get(w, unk) for w in doc], 0
        for doc in tokenize(neg_pattern):
            yield [word_idx.get(w, unk) for w in doc], 1

    return reader


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            ln = int(rng.randint(8, 60))
            lo = 2 + label * (_VOCAB // 2)
            hi = lo + _VOCAB // 2 - 2
            words = rng.randint(lo, hi, ln).tolist()
            yield words, label
    return reader


def train(word_idx=None):
    if _have_real():
        return _real_reader(re.compile(r"aclImdb/train/pos/.*\.txt$"),
                            re.compile(r"aclImdb/train/neg/.*\.txt$"),
                            word_dict() if word_idx is None else word_idx)
    return _synthetic_reader(_TRAIN_N, 0)


def test(word_idx=None):
    if _have_real():
        return _real_reader(re.compile(r"aclImdb/test/pos/.*\.txt$"),
                            re.compile(r"aclImdb/test/neg/.*\.txt$"),
                            word_dict() if word_idx is None else word_idx)
    return _synthetic_reader(_TEST_N, 1)
