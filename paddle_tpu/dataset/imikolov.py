"""PTB language-model data (reference: python/paddle/v2/dataset/
imikolov.py, used by the word2vec book chapter). Schema: n-gram tuples of
int64 word ids (NGRAM) or (src_seq, trg_seq) pairs (SEQ).

Real data: drop `simple-examples.tgz` (the Mikolov PTB tarball, reference
imikolov.py URL) under DATA_HOME/imikolov/ and build_dict/train/test parse
it exactly as the reference (imikolov.py:36-104): word freq over
ptb.train+ptb.valid with '<s>'/'<e>' counted per line, min_word_freq
cutoff, freq-then-lex sort, '<unk>' last; NGRAM slides a window over
'<s>' + words + '<e>', SEQ yields ('<s>'+ids, ids+'<e>') skipping
sentences longer than n. Synthetic surrogate otherwise: deterministic
successor chains so the n-gram task is learnable."""

from __future__ import annotations

import collections
import tarfile

import numpy as np

from . import common

_VOCAB = 2074
_TRAIN_N, _TEST_N = 4096, 512
_FILE = "simple-examples.tgz"
_TRAIN_MEMBER = "simple-examples/data/ptb.train.txt"
_TEST_MEMBER = "simple-examples/data/ptb.valid.txt"


class DataType:
    NGRAM = 1
    SEQ = 2


def _have_real():
    return common.have_real_data("imikolov", _FILE)


def _extract(tf, member):
    # upstream tarballs prefix members with './'
    try:
        return tf.extractfile(member)
    except KeyError:
        return tf.extractfile("./" + member)


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="ignore")
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50):
    if not _have_real():
        return {f"w{i}": i for i in range(_VOCAB)}
    with tarfile.open(common.cache_path("imikolov", _FILE)) as tf:
        word_freq = word_count(_extract(tf, _TEST_MEMBER),
                               word_count(_extract(tf, _TRAIN_MEMBER)))
    word_freq.pop("<unk>", None)  # re-added as the last index below
    word_freq = [x for x in word_freq.items() if x[1] > min_word_freq]
    word_freq_sorted = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words = [w for w, _ in word_freq_sorted]
    word_idx = dict(zip(words, range(len(words))))
    word_idx["<unk>"] = len(words)
    return word_idx


def _real_reader(member, word_idx, n, data_type):
    def reader():
        with tarfile.open(common.cache_path("imikolov", _FILE)) as tf:
            unk = word_idx["<unk>"]
            for line in _extract(tf, member):
                if isinstance(line, bytes):
                    line = line.decode("utf-8", errors="ignore")
                if data_type == DataType.NGRAM:
                    assert n > -1, "Invalid gram length"
                    toks = ["<s>"] + line.strip().split() + ["<e>"]
                    if len(toks) >= n:
                        ids = [word_idx.get(w, unk) for w in toks]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, unk)
                           for w in line.strip().split()]
                    src_seq = [word_idx["<s>"]] + ids
                    trg_seq = ids + [word_idx["<e>"]]
                    if n > 0 and len(src_seq) > n:
                        continue
                    yield src_seq, trg_seq
                else:
                    raise AssertionError("Unknown data type")
    return reader


def _synthetic_reader(n_samples, n, seed, data_type):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            # active ids restricted to a subset so each id recurs often
            # enough for the n-gram task to be learnable in a short budget
            start = int(rng.randint(0, 256))
            # deterministic successor chain => learnable next-word task
            gram = [(start + 7 * k) % _VOCAB for k in range(max(n, 2))]
            if data_type == DataType.NGRAM:
                yield tuple(gram)
            else:
                yield gram, gram[1:] + [(gram[-1] + 7) % _VOCAB]
    return reader


def train(word_idx=None, n=5, data_type=DataType.NGRAM):
    if _have_real():
        return _real_reader(_TRAIN_MEMBER, build_dict() if word_idx is None else word_idx, n,
                            data_type)
    return _synthetic_reader(_TRAIN_N, n, 0, data_type)


def test(word_idx=None, n=5, data_type=DataType.NGRAM):
    if _have_real():
        return _real_reader(_TEST_MEMBER, build_dict() if word_idx is None else word_idx, n,
                            data_type)
    return _synthetic_reader(_TEST_N, n, 1, data_type)
