"""PTB language-model n-grams (reference: python/paddle/v2/dataset/
imikolov.py, used by the word2vec book chapter). Schema: n-gram of int64
word ids. Synthetic surrogate: a Markov-ish id chain so the n-gram
prediction task is learnable."""

from __future__ import annotations

import numpy as np

_VOCAB = 2074
_TRAIN_N, _TEST_N = 4096, 512


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n_samples, n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            # active ids restricted to a subset so each id recurs often
            # enough for the n-gram task to be learnable in a short budget
            start = int(rng.randint(0, 256))
            # deterministic successor chain => learnable next-word task
            gram = [(start + 7 * k) % _VOCAB for k in range(n)]
            yield tuple(gram)
    return reader


def train(word_idx=None, n=5):
    return _reader(_TRAIN_N, n, 0)


def test(word_idx=None, n=5):
    return _reader(_TEST_N, n, 1)
