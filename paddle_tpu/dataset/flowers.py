"""Flowers-102 (reference: python/paddle/v2/dataset/flowers.py). Schema:
(3*224*224 float32 image in [0,1], int64 label in [0,102)). Synthetic
surrogate: per-class hue blob on a textured background, generated lazily
per sample so the 224x224 images never materialize as one big array."""

from __future__ import annotations

import numpy as np

CLASS_NUM = 102
_TRAIN_N, _TEST_N, _VALID_N = 512, 128, 128
_H = _W = 224


def _sample(rng, classes):
    label = int(rng.randint(0, classes))
    img = rng.rand(3, _H, _W).astype(np.float32) * 0.2
    ch = label % 3
    r0 = (label * 37) % (_H - 64)
    c0 = (label * 53) % (_W - 64)
    img[ch, r0:r0 + 64, c0:c0 + 64] += 0.7
    return np.clip(img, 0, 1).reshape(-1), label


def _reader(n, seed, classes=CLASS_NUM):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield _sample(rng, classes)
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(_TRAIN_N, 0)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(_TEST_N, 1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(_VALID_N, 2)
