"""Flowers-102 (reference: python/paddle/v2/dataset/flowers.py). Schema:
(3*224*224 float32 image in [0,1], int64 label in [0,102)).

Real data: drop `102flowers.tgz`, `imagelabels.mat`, `setid.mat` (the
VGG 102-flowers release, reference flowers.py:34-41) under
DATA_HOME/flowers/ and train/test/valid parse them as the reference does
(flowers.py:73-123): setid.mat's trnid/tstid/valid index the tarball's
jpg/image_%05d.jpg members, imagelabels.mat supplies 1-based labels, each
image is resized (short side 256), center-cropped to 224, emitted CHW
flattened with the label shifted to 0-based. Pixels here stay in [0,1]
(this stack's CNN stems normalize internally) where the reference's
default mapper subtracted BGR channel means. Synthetic surrogate
otherwise: per-class hue blob on a textured background."""

from __future__ import annotations

import io
import tarfile

import numpy as np

from . import common

CLASS_NUM = 102
_TRAIN_N, _TEST_N, _VALID_N = 512, 128, 128
_H = _W = 224

_DATA_FILE = "102flowers.tgz"
_LABEL_FILE = "imagelabels.mat"
_SETID_FILE = "setid.mat"
TRAIN_FLAG, TEST_FLAG, VALID_FLAG = "trnid", "tstid", "valid"


def _have_real():
    return all(common.have_real_data("flowers", f)
               for f in (_DATA_FILE, _LABEL_FILE, _SETID_FILE))


def _transform(img_bytes):
    """Resize short side to 256, center-crop 224, CHW float32 in [0,1]."""
    from PIL import Image

    img = Image.open(io.BytesIO(img_bytes)).convert("RGB")
    w, h = img.size
    scale = 256.0 / min(w, h)
    img = img.resize((max(int(w * scale), 224), max(int(h * scale), 224)))
    w, h = img.size
    x0, y0 = (w - _W) // 2, (h - _H) // 2
    img = img.crop((x0, y0, x0 + _W, y0 + _H))
    arr = np.asarray(img, np.float32) / 255.0          # HWC
    return arr.transpose(2, 0, 1).reshape(-1)          # CHW flat


def _real_reader(flag):
    import scipy.io as scio

    def reader():
        labels = scio.loadmat(
            common.cache_path("flowers", _LABEL_FILE))["labels"][0]
        indexes = scio.loadmat(
            common.cache_path("flowers", _SETID_FILE))[flag][0]
        wanted = {f"jpg/image_{i:05d}.jpg": int(labels[i - 1])
                  for i in indexes}
        with tarfile.open(common.cache_path("flowers", _DATA_FILE)) as tf:
            for member in tf:
                label = wanted.get(member.name)
                if label is None:
                    continue
                data = tf.extractfile(member).read()
                yield _transform(data), label - 1
    return reader


def _sample(rng, classes):
    label = int(rng.randint(0, classes))
    img = rng.rand(3, _H, _W).astype(np.float32) * 0.2
    ch = label % 3
    r0 = (label * 37) % (_H - 64)
    c0 = (label * 53) % (_W - 64)
    img[ch, r0:r0 + 64, c0:c0 + 64] += 0.7
    return np.clip(img, 0, 1).reshape(-1), label


def _synthetic_reader(n, seed, classes=CLASS_NUM):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield _sample(rng, classes)
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    if _have_real():
        return _real_reader(TRAIN_FLAG)
    return _synthetic_reader(_TRAIN_N, 0)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    if _have_real():
        return _real_reader(TEST_FLAG)
    return _synthetic_reader(_TEST_N, 1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    if _have_real():
        return _real_reader(VALID_FLAG)
    return _synthetic_reader(_VALID_N, 2)
