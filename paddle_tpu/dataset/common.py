"""Dataset infrastructure (reference: python/paddle/v2/dataset/common.py —
download + md5 cache).

This environment has no network egress, so each dataset module falls back to
a deterministic synthetic surrogate with the real schema (same field types,
shapes, vocab sizes) when the cached real data is absent. Real data dropped
into DATA_HOME is picked up transparently."""

from __future__ import annotations

import os

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def cache_path(module: str, filename: str) -> str:
    d = os.path.join(DATA_HOME, module)
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, filename)


def have_real_data(module: str, filename: str) -> bool:
    return os.path.exists(os.path.join(DATA_HOME, module, filename))


def download(url: str, module: str, md5sum: str = None,
             save_name: str = None):
    """API-compatible stub for the reference's downloader: with no egress it
    only resolves already-cached files."""
    filename = save_name or url.split("/")[-1]
    path = os.path.join(DATA_HOME, module, filename)
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f"no network egress: place {filename} under {DATA_HOME}/{module}/ "
        "to use real data (synthetic surrogate is used by default)")
