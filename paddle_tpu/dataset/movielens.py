"""MovieLens-1M (reference: python/paddle/v2/dataset/movielens.py, used by
the recommender_system book chapter). Schema per sample:
(user_id, gender_id, age_id, job_id, movie_id, category_ids[var],
 title_ids[var], score). Synthetic surrogate keeps the reference's id
spaces and makes score a learnable function of the ids."""

from __future__ import annotations

import numpy as np

USER_N = 6040
MOVIE_N = 3952
GENDER_N = 2
AGE_N = 7
JOB_N = 21
CATEGORY_N = 18
TITLE_VOCAB = 5175

_TRAIN_N, _TEST_N = 4096, 512


def max_user_id():
    return USER_N


def max_movie_id():
    return MOVIE_N


def max_job_id():
    return JOB_N - 1


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def movie_categories():
    return {f"cat{i}": i for i in range(CATEGORY_N)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(TITLE_VOCAB)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = int(rng.randint(1, USER_N + 1))
            gender = int(rng.randint(0, GENDER_N))
            age = int(rng.randint(0, AGE_N))
            job = int(rng.randint(0, JOB_N))
            mid = int(rng.randint(1, MOVIE_N + 1))
            ncat = int(rng.randint(1, 4))
            cats = rng.randint(0, CATEGORY_N, ncat).tolist()
            ntit = int(rng.randint(1, 6))
            titles = rng.randint(0, TITLE_VOCAB, ntit).tolist()
            # learnable score: smooth function of user/movie ids
            score = 1 + ((uid * 31 + mid * 17) % 5)
            yield [uid], [gender], [age], [job], [mid], cats, titles, \
                [float(score)]
    return reader


def train():
    return _reader(_TRAIN_N, 0)


def test():
    return _reader(_TEST_N, 1)
