"""MovieLens-1M (reference: python/paddle/v2/dataset/movielens.py, used by
the recommender_system book chapter). Schema per sample:
([user_id], [gender_id], [age_id], [job_id], [movie_id],
 category_ids[var], title_ids[var], [score]).

Real data: drop `ml-1m.zip` (GroupLens, reference movielens.py:39) under
DATA_HOME/movielens/ and the readers parse movies.dat / users.dat /
ratings.dat exactly as the reference (movielens.py:102-159): '::'-split
records, title '(year)' suffix stripped, category and title-word dicts
built from the corpus, age bucketed by age_table, deterministic 10%
train/test split via random.Random(0), rating mapped to 2r-5. Synthetic
surrogate otherwise (same id spaces, learnable score)."""

from __future__ import annotations

import random
import re
import zipfile

import numpy as np

from . import common

USER_N = 6040
MOVIE_N = 3952
GENDER_N = 2
AGE_N = 7
JOB_N = 21
CATEGORY_N = 18
TITLE_VOCAB = 5175

_TRAIN_N, _TEST_N = 4096, 512
_FILE = "ml-1m.zip"

_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]

# parsed-once metadata caches (reference movielens.py:96-99)
_MOVIE_INFO = None      # id -> (movie_id, [cat ids], [title word ids])
_USER_INFO = None       # id -> (user_id, gender01, age_idx, job_id)
_TITLE_DICT = None
_CATEGORIES_DICT = None


def _have_real():
    return common.have_real_data("movielens", _FILE)


def _init_meta():
    """Parse movies.dat + users.dat once (reference movielens.py:102-142)."""
    global _MOVIE_INFO, _USER_INFO, _TITLE_DICT, _CATEGORIES_DICT
    if _MOVIE_INFO is not None:
        return
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    raw_movies, title_words, categories = {}, set(), set()
    with zipfile.ZipFile(common.cache_path("movielens", _FILE)) as pkg:
        with pkg.open("ml-1m/movies.dat") as f:
            for line in f:
                line = line.decode("latin1").strip()
                if not line:
                    continue
                movie_id, title, cats = line.split("::")
                cats = cats.split("|")
                categories.update(cats)
                title = pattern.match(title).group(1).strip()
                raw_movies[int(movie_id)] = (title, cats)
                for w in title.split():
                    title_words.add(w.lower())
        _TITLE_DICT = {w: i for i, w in enumerate(sorted(title_words))}
        _CATEGORIES_DICT = {c: i for i, c in enumerate(sorted(categories))}
        _MOVIE_INFO = {
            mid: (mid, [_CATEGORIES_DICT[c] for c in cats],
                  [_TITLE_DICT[w.lower()] for w in title.split()])
            for mid, (title, cats) in raw_movies.items()}
        _USER_INFO = {}
        with pkg.open("ml-1m/users.dat") as f:
            for line in f:
                line = line.decode("latin1").strip()
                if not line:
                    continue
                uid, gender, age, job = line.split("::")[:4]
                _USER_INFO[int(uid)] = (
                    int(uid), 0 if gender == "M" else 1,
                    _AGE_TABLE.index(int(age)), int(job))


def _real_reader(is_test, test_ratio=0.1, rand_seed=0):
    """ratings.dat split deterministically into train/test by
    random.Random(rand_seed) draws (reference movielens.py:145-159)."""
    def reader():
        _init_meta()
        rand = random.Random(x=rand_seed)
        with zipfile.ZipFile(common.cache_path("movielens", _FILE)) as pkg:
            with pkg.open("ml-1m/ratings.dat") as f:
                for line in f:
                    line = line.decode("latin1").strip()
                    if not line:
                        continue
                    if (rand.random() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ts = line.split("::")
                    u = _USER_INFO[int(uid)]
                    m = _MOVIE_INFO[int(mid)]
                    score = float(rating) * 2 - 5.0
                    yield [u[0]], [u[1]], [u[2]], [u[3]], [m[0]], m[1], \
                        m[2], [score]
    return reader


def max_user_id():
    if _have_real():
        _init_meta()
        return max(_USER_INFO)
    return USER_N


def max_movie_id():
    if _have_real():
        _init_meta()
        return max(_MOVIE_INFO)
    return MOVIE_N


def max_job_id():
    if _have_real():
        _init_meta()
        return max(u[3] for u in _USER_INFO.values())
    return JOB_N - 1


def age_table():
    return list(_AGE_TABLE)


def movie_categories():
    if _have_real():
        _init_meta()
        return dict(_CATEGORIES_DICT)
    return {f"cat{i}": i for i in range(CATEGORY_N)}


def get_movie_title_dict():
    if _have_real():
        _init_meta()
        return dict(_TITLE_DICT)
    return {f"t{i}": i for i in range(TITLE_VOCAB)}


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = int(rng.randint(1, USER_N + 1))
            gender = int(rng.randint(0, GENDER_N))
            age = int(rng.randint(0, AGE_N))
            job = int(rng.randint(0, JOB_N))
            mid = int(rng.randint(1, MOVIE_N + 1))
            ncat = int(rng.randint(1, 4))
            cats = rng.randint(0, CATEGORY_N, ncat).tolist()
            ntit = int(rng.randint(1, 6))
            titles = rng.randint(0, TITLE_VOCAB, ntit).tolist()
            # learnable score: smooth function of user/movie ids
            score = 1 + ((uid * 31 + mid * 17) % 5)
            yield [uid], [gender], [age], [job], [mid], cats, titles, \
                [float(score)]
    return reader


def train():
    if _have_real():
        return _real_reader(is_test=False)
    return _synthetic_reader(_TRAIN_N, 0)


def test():
    if _have_real():
        return _real_reader(is_test=True)
    return _synthetic_reader(_TEST_N, 1)
