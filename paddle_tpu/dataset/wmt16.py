"""WMT-16 en-de (reference: python/paddle/v2/dataset/wmt16.py). Schema
matches the reference's BPE-token loaders: (src_ids, trg_ids_with_<s>,
trg_ids_next_with_<e>) int64 sequences, with per-language dict sizes.
Synthetic surrogate reuses the wmt14 construction (deterministic
learnable mapping) with independent source/target vocab sizes."""

from __future__ import annotations

import numpy as np

_START, _END, _UNK = 0, 1, 2
_TRAIN_N, _TEST_N, _VALID_N = 2048, 256, 256


def _reader(n, src_dict_size, trg_dict_size, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(3, 12))
            src = rng.randint(3, src_dict_size, ln).tolist()
            trg = [(src[0] * 3 + 1) % (trg_dict_size - 3) + 3]
            for _k in range(ln - 1):
                trg.append((trg[-1] * 5 + 7) % (trg_dict_size - 3) + 3)
            yield src, [_START] + trg, trg + [_END]
    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(_TRAIN_N, src_dict_size, trg_dict_size, 0)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(_TEST_N, src_dict_size, trg_dict_size, 1)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(_VALID_N, src_dict_size, trg_dict_size, 2)


def get_dict(lang, dict_size, reverse=False):
    d = {"<s>": _START, "<e>": _END, "<unk>": _UNK}
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d
