"""WMT-16 en-de (reference: python/paddle/v2/dataset/wmt16.py). Schema:
(src_ids_with_<s>/<e>, trg_ids_with_<s>, trg_ids_next_with_<e>) int64
sequences, with per-language dict sizes.

Real data: drop `wmt16.tar.gz` (the reference's tokenized Multi30k-style
tarball, wmt16.py:46-48: members wmt16/train, wmt16/val, wmt16/test with
tab-separated en\\tde lines) under DATA_HOME/wmt16/ and
train/test/validation/get_dict parse it exactly as the reference
(wmt16.py:58-135): per-language frequency dicts built from the train
split with <s>/<e>/<unk> prepended and cached as {lang}_{size}.dict,
source wrapped in <s>/<e>, target emitted as (<s>+ids, ids+<e>).
Synthetic surrogate otherwise (deterministic learnable mapping)."""

from __future__ import annotations

import os
import tarfile
from collections import defaultdict

import numpy as np

from . import common

_START, _END, _UNK = 0, 1, 2
START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"
TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220
_TRAIN_N, _TEST_N, _VALID_N = 2048, 256, 256
_FILE = "wmt16.tar.gz"


def _have_real():
    return common.have_real_data("wmt16", _FILE)


def _build_dict(tar_file, dict_size, save_path, lang):
    """Frequency dict over the train split (reference wmt16.py:58-74)."""
    word_dict = defaultdict(int)
    with tarfile.open(tar_file, mode="r") as f:
        for line in f.extractfile("wmt16/train"):
            line_split = line.decode("utf-8", errors="ignore").strip() \
                             .split("\t")
            if len(line_split) != 2:
                continue
            sen = line_split[0] if lang == "en" else line_split[1]
            for w in sen.split():
                word_dict[w] += 1
    with open(save_path, "w") as fout:
        fout.write(f"{START_MARK}\n{END_MARK}\n{UNK_MARK}\n")
        for idx, word in enumerate(
                sorted(word_dict.items(), key=lambda x: (-x[1], x[0]))):
            if idx + 3 == dict_size:
                break
            fout.write(f"{word[0]}\n")


def _clamp(src_dict_size, trg_dict_size, src_lang):
    """Reference wmt16.py __get_dict_size: cap requested sizes at the
    corpus vocab so the cached dict file is complete and the freshness
    check below never triggers a per-epoch rebuild."""
    src_total = TOTAL_EN_WORDS if src_lang == "en" else TOTAL_DE_WORDS
    trg_total = TOTAL_DE_WORDS if src_lang == "en" else TOTAL_EN_WORDS
    return min(src_dict_size, src_total), min(trg_dict_size, trg_total)


def _load_dict(dict_size, lang, reverse=False):
    tar_file = common.cache_path("wmt16", _FILE)
    dict_path = os.path.join(common.DATA_HOME, "wmt16",
                             f"{lang}_{dict_size}.dict")
    # the size is baked into the filename, so an existing file is valid:
    # fewer lines than dict_size just means the corpus vocab ran out
    # (rebuilding could never add more); more means corruption
    if not os.path.exists(dict_path) or (
            len(open(dict_path).readlines()) > dict_size):
        _build_dict(tar_file, dict_size, dict_path, lang)
    word_dict = {}
    with open(dict_path) as fdict:
        for idx, line in enumerate(fdict):
            if reverse:
                word_dict[idx] = line.strip()
            else:
                word_dict[line.strip()] = idx
    return word_dict


def _real_reader(file_name, src_dict_size, trg_dict_size, src_lang):
    src_dict_size, trg_dict_size = _clamp(src_dict_size, trg_dict_size,
                                          src_lang)

    def reader():
        src_dict = _load_dict(src_dict_size, src_lang)
        trg_dict = _load_dict(trg_dict_size,
                              "de" if src_lang == "en" else "en")
        start_id, end_id, unk_id = (src_dict[START_MARK],
                                    src_dict[END_MARK],
                                    src_dict[UNK_MARK])
        src_col = 0 if src_lang == "en" else 1
        trg_col = 1 - src_col
        with tarfile.open(common.cache_path("wmt16", _FILE)) as f:
            for line in f.extractfile(file_name):
                parts = line.decode("utf-8", errors="ignore").strip() \
                            .split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + [src_dict.get(w, unk_id)
                                        for w in parts[src_col].split()] \
                    + [end_id]
                trg_ids = [trg_dict.get(w, unk_id)
                           for w in parts[trg_col].split()]
                trg_ids_next = trg_ids + [end_id]
                trg_ids = [start_id] + trg_ids
                yield src_ids, trg_ids, trg_ids_next
    return reader


def _synthetic_reader(n, src_dict_size, trg_dict_size, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            ln = int(rng.randint(3, 12))
            src = rng.randint(3, src_dict_size, ln).tolist()
            trg = [(src[0] * 3 + 1) % (trg_dict_size - 3) + 3]
            for _k in range(ln - 1):
                trg.append((trg[-1] * 5 + 7) % (trg_dict_size - 3) + 3)
            yield src, [_START] + trg, trg + [_END]
    return reader


def _check_lang(src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError("wrong language type: only 'en' and 'de'")


def train(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    if _have_real():
        return _real_reader("wmt16/train", src_dict_size, trg_dict_size,
                            src_lang)
    return _synthetic_reader(_TRAIN_N, src_dict_size, trg_dict_size, 0)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    if _have_real():
        return _real_reader("wmt16/test", src_dict_size, trg_dict_size,
                            src_lang)
    return _synthetic_reader(_TEST_N, src_dict_size, trg_dict_size, 1)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    if _have_real():
        return _real_reader("wmt16/val", src_dict_size, trg_dict_size,
                            src_lang)
    return _synthetic_reader(_VALID_N, src_dict_size, trg_dict_size, 2)


def get_dict(lang, dict_size, reverse=False):
    if _have_real():
        dict_size = min(dict_size, (TOTAL_EN_WORDS if lang == "en"
                                    else TOTAL_DE_WORDS))
        return _load_dict(dict_size, lang, reverse)
    d = {START_MARK: _START, END_MARK: _END, UNK_MARK: _UNK}
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d
