"""MNIST (reference: python/paddle/v2/dataset/mnist.py). Schema: 784 float32
pixels in [-1, 1], int64 label 0-9. Synthetic surrogate: class-dependent
blob patterns, learnable by mlp/conv book models."""

from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common

_TRAIN_N, _TEST_N = 8192, 1024


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n)
    imgs = np.zeros((n, 28, 28), np.float32)
    for k in range(n):
        c = labels[k]
        r0, c0 = (c // 5) * 12 + 2, (c % 5) * 5 + 1
        imgs[k, r0:r0 + 12, c0:c0 + 4] = 1.0
    imgs += rng.randn(n, 28, 28).astype(np.float32) * 0.2
    imgs = np.clip(imgs, 0, 1) * 2.0 - 1.0
    return imgs.reshape(n, 784), labels.astype(np.int64)


def _read_idx(img_path, lab_path):
    with gzip.open(img_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        imgs = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
    with gzip.open(lab_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    return (imgs.astype(np.float32) / 127.5 - 1.0), labels.astype(np.int64)


def _load(split):
    prefix = "train" if split == "train" else "t10k"
    img = f"{prefix}-images-idx3-ubyte.gz"
    lab = f"{prefix}-labels-idx1-ubyte.gz"
    if common.have_real_data("mnist", img) and \
            common.have_real_data("mnist", lab):
        return _read_idx(common.cache_path("mnist", img),
                         common.cache_path("mnist", lab))
    if split == "train":
        return _synthetic(_TRAIN_N, 0)
    return _synthetic(_TEST_N, 1)


def _reader(split):
    def reader():
        imgs, labels = _load(split)
        for i in range(len(imgs)):
            yield imgs[i], int(labels[i])
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
