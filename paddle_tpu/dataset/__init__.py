"""Datasets (reference: python/paddle/v2/dataset/ — 14 loaders with
download+cache). Zero-egress build: each module serves a deterministic
synthetic surrogate with the real schema unless real files are present
under common.DATA_HOME (see common.py)."""

from . import (cifar, common, conll05, flowers, imdb, imikolov, mnist,
               movielens, mq2007, sentiment, uci_housing, voc2012, wmt14,
               wmt16)

__all__ = ["cifar", "common", "conll05", "flowers", "imdb", "imikolov",
           "mnist", "movielens", "mq2007", "sentiment", "uci_housing",
           "voc2012", "wmt14", "wmt16"]
