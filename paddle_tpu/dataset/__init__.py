"""Datasets (reference: python/paddle/v2/dataset/ — 14 loaders with
download+cache). Zero-egress build: every module parses the reference's
real on-disk format when the file is present under common.DATA_HOME
(mnist idx, cifar pickle tarballs, aclImdb tar, PTB tgz, ml-1m zip,
conll05st tar+dicts, nltk movie_reviews dir, wmt14/wmt16 tarballs,
102flowers tgz+mat, VOC tar, uci housing.data, mq2007 txt) and otherwise
serves a deterministic synthetic surrogate with the same schema — all 14
real parsers are exercised against format-faithful fixtures in
tests/test_dataset_real_formats.py."""

from . import (cifar, common, conll05, flowers, imdb, imikolov, mnist,
               movielens, mq2007, sentiment, uci_housing, voc2012, wmt14,
               wmt16)

__all__ = ["cifar", "common", "conll05", "flowers", "imdb", "imikolov",
           "mnist", "movielens", "mq2007", "sentiment", "uci_housing",
           "voc2012", "wmt14", "wmt16"]
