"""NLTK movie-review sentiment corpus (reference:
python/paddle/v2/dataset/sentiment.py). Schema: (word-id sequence, label
0/1 = negative/positive).

Real data: drop the nltk corpus directory `corpora/movie_reviews/` (with
neg/*.txt and pos/*.txt, exactly what `nltk.download('movie_reviews')`
unpacks — reference sentiment.py:36-50) under DATA_HOME/sentiment/ and
get_word_dict/train/test parse it as the reference does
(sentiment.py:53-100): frequency-sorted word dict over the whole corpus,
neg/pos files interleaved so train/test splits stay balanced, word
tokenization approximating nltk's (word chars and punctuation runs as
separate tokens, lowercased). Synthetic surrogate otherwise."""

from __future__ import annotations

import os
import re

import numpy as np

from . import common

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 2048

_TOKEN = re.compile(r"[A-Za-z0-9_']+|[^\sA-Za-z0-9_']")


def _corpus_dir():
    for sub in ("corpora/movie_reviews", "movie_reviews"):
        d = os.path.join(common.DATA_HOME, "sentiment", sub)
        if os.path.isdir(d):
            return d
    return None


def _files(cat):
    d = _corpus_dir()
    sub = os.path.join(d, cat)
    return [os.path.join(sub, f) for f in sorted(os.listdir(sub))
            if f.endswith(".txt")]


def _words(path):
    with open(path, encoding="utf-8", errors="ignore") as f:
        return [w.lower() for w in _TOKEN.findall(f.read())]


def get_word_dict():
    """[(word, id)] sorted by corpus frequency, most frequent first
    (reference sentiment.py:53-71)."""
    if _corpus_dir() is None:
        return [(f"w{i}", i) for i in range(_VOCAB)]
    freq = {}
    for cat in ("neg", "pos"):
        for path in _files(cat):
            for w in _words(path):
                freq[w] = freq.get(w, 0) + 1
    ranked = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    return [(w, i) for i, (w, _) in enumerate(ranked)]


_DATA_CACHE = None  # parse-once (movielens._init_meta pattern)


def _load_real():
    """Interleave neg/pos files (sentiment.py:74-100: label 0=neg, 1=pos)
    so any prefix split is balanced. Parsed once per process — a reader
    is re-invoked every epoch and the corpus is 2000 files."""
    global _DATA_CACHE
    if _DATA_CACHE is not None:
        return _DATA_CACHE
    words_ids = dict(get_word_dict())
    data = []
    for neg, pos in zip(_files("neg"), _files("pos")):
        data.append(([words_ids[w] for w in _words(neg)], 0))
        data.append(([words_ids[w] for w in _words(pos)], 1))
    _DATA_CACHE = data
    return data


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            ln = int(rng.randint(10, 50))
            lo = 2 + label * (_VOCAB // 2)
            hi = lo + _VOCAB // 2 - 2
            yield rng.randint(lo, hi, ln).tolist(), label
    return reader


def _real_reader(lo, hi):
    def reader():
        data = _load_real()
        n = len(data)
        lo_i = min(lo, n)
        hi_i = min(hi, n)
        for sample in data[lo_i:hi_i]:
            yield sample
    return reader


def train():
    if _corpus_dir() is not None:
        return _real_reader(0, NUM_TRAINING_INSTANCES)
    return _synthetic_reader(NUM_TRAINING_INSTANCES, 0)


def test():
    if _corpus_dir() is not None:
        return _real_reader(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES)
    return _synthetic_reader(NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, 1)
