"""NLTK movie-review sentiment corpus (reference:
python/paddle/v2/dataset/sentiment.py). Schema: (word-id sequence, label
0/1 = negative/positive). Synthetic surrogate: sentiment-biased vocab
regions (same construction as the imdb surrogate, smaller vocab)."""

from __future__ import annotations

import numpy as np

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 2048


def get_word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            ln = int(rng.randint(10, 50))
            lo = 2 + label * (_VOCAB // 2)
            hi = lo + _VOCAB // 2 - 2
            yield rng.randint(lo, hi, ln).tolist(), label
    return reader


def train():
    return _reader(NUM_TRAINING_INSTANCES, 0)


def test():
    return _reader(NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, 1)
