"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py). Schema:
3*32*32 float32 in [0,1], int64 label.

Real data: drop `cifar-10-python.tar.gz` / `cifar-100-python.tar.gz` (the
upstream pickled tarballs, reference cifar.py:40-43) under
DATA_HOME/cifar/ and the readers parse them exactly as the reference does
(cifar.py:46-64: tar members matched by substring, per-batch pickle dicts
with 'data' uint8 [N, 3072] and 'labels'/'fine_labels'). Without the
files, a deterministic synthetic surrogate with the same schema serves
(class-colored quadrant blobs)."""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from . import common

_TRAIN_N, _TEST_N = 4096, 512
_FILE10 = "cifar-10-python.tar.gz"
_FILE100 = "cifar-100-python.tar.gz"


def _synthetic(n, classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    imgs = rng.rand(n, 3, 32, 32).astype(np.float32) * 0.25
    for k in range(n):
        c = int(labels[k])
        ch = c % 3
        q = (c // 3) % 4
        r0, c0 = (q // 2) * 16, (q % 2) * 16
        imgs[k, ch, r0:r0 + 16, c0:c0 + 16] += 0.7
    return np.clip(imgs, 0, 1).reshape(n, 3 * 32 * 32), labels.astype(np.int64)


def _synthetic_reader(n, classes, seed):
    def reader():
        imgs, labels = _synthetic(n, classes, seed)
        for i in range(n):
            yield imgs[i], int(labels[i])
    return reader


def _real_reader(filename, sub_name):
    """Reference cifar.py:46-64: iterate tar members whose name contains
    sub_name ('data_batch'/'test_batch' for 10, 'train'/'test' for 100),
    unpickle each batch, yield (pixels/255 float32, int label)."""
    path = common.cache_path("cifar", filename)

    def reader():
        with tarfile.open(path, mode="r") as f:
            names = [m.name for m in f if sub_name in m.name]
            for name in sorted(names):
                batch = pickle.load(f.extractfile(name), encoding="latin1")
                data = batch["data"]
                labels = batch.get("labels", batch.get("fine_labels"))
                assert labels is not None
                for sample, label in zip(data, labels):
                    yield (np.asarray(sample, np.float32) / 255.0,
                           int(label))

    return reader


def _reader(filename, sub_name, n, classes, seed):
    if common.have_real_data("cifar", filename):
        return _real_reader(filename, sub_name)
    return _synthetic_reader(n, classes, seed)


def train10():
    return _reader(_FILE10, "data_batch", _TRAIN_N, 10, 0)


def test10():
    return _reader(_FILE10, "test_batch", _TEST_N, 10, 1)


def train100():
    return _reader(_FILE100, "train", _TRAIN_N, 100, 2)


def test100():
    return _reader(_FILE100, "test", _TEST_N, 100, 3)
