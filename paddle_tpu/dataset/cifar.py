"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py). Schema:
3*32*32 float32 in [0,1], int64 label. Synthetic surrogate: class-colored
quadrant blobs."""

from __future__ import annotations

import numpy as np

_TRAIN_N, _TEST_N = 4096, 512


def _synthetic(n, classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    imgs = rng.rand(n, 3, 32, 32).astype(np.float32) * 0.25
    for k in range(n):
        c = int(labels[k])
        ch = c % 3
        q = (c // 3) % 4
        r0, c0 = (q // 2) * 16, (q % 2) * 16
        imgs[k, ch, r0:r0 + 16, c0:c0 + 16] += 0.7
    return np.clip(imgs, 0, 1).reshape(n, 3 * 32 * 32), labels.astype(np.int64)


def _reader(n, classes, seed):
    def reader():
        imgs, labels = _synthetic(n, classes, seed)
        for i in range(n):
            yield imgs[i], int(labels[i])
    return reader


def train10():
    return _reader(_TRAIN_N, 10, 0)


def test10():
    return _reader(_TEST_N, 10, 1)


def train100():
    return _reader(_TRAIN_N, 100, 2)


def test100():
    return _reader(_TEST_N, 100, 3)
