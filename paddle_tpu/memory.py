"""Memory observability: HBM accounting, peak attribution, OOM forensics.

The reference framework's Memory layer (buffer allocators + the
memory_optimize liveness pass, paddle/fluid/memory/*, transpiler's
memory_optimize) is subsumed by PJRT/XLA here by design — XLA's buffer
assignment decides every allocation. That leaves the framework blind to
the resource that actually bounds TPU training: HBM. This module is the
space-side sibling of telemetry.py (time) and inspector.py (numerics):

1. **Static analysis** — after a block's first jit compile the executor
   calls `on_compile`, which re-lowers the SAME jitted fn from avals
   (the `_hlo_supplier` idiom: shapes only, donated buffers never kept
   alive) and captures `Compiled.memory_analysis()` — argument / output /
   temp / alias / generated-code bytes — into a `ProgramMemory` record,
   `memory_*_bytes` gauges and the step-event log. A scheduled-HLO
   liveness walk (`hlo_peak_liveness`) attributes the high-water mark to
   the top-k IR ops through the same `pd.<type>` named-scope metadata the
   profiler's device table uses (xplane.hlo_op_names).
2. **Live accounting** — a `MemoryTracker` samples `device.memory_stats()`
   (TPU) or falls back to summing `jax.live_arrays()` (CPU backends
   return None) per Executor.run, classifies state into
   params / opt-state / feeds / activations by scope metadata, and feeds
   the `hbm_bytes_in_use` / `hbm_peak_bytes` gauges and the inspector
   flight-recorder ring.
3. **What-if estimation** — `HeadroomModel` fits peak(b) = fixed +
   per_sample*b from static analyses at two batch sizes, predicts the
   max batch under an HBM budget, and validates the extrapolation
   against a fresh analysis at the predicted batch (`what_if`).
4. **OOM forensics** — `maybe_oom_error` turns a raw RESOURCE_EXHAUSTED
   (jax XlaRuntimeError) into a structured `errors.OOMError` carrying
   the breakdown, top live buffers, donation losses and concrete
   suggestions; the executor raises it through the inspector crash-report
   path. Surfaced by `python -m paddle_tpu memory` (cli.py).

Everything here must be advisory: analysis/tracking failures are caught
at the executor call sites and never fail a training step.
"""

from __future__ import annotations

import math
import re
import threading
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import flags
from . import telemetry

__all__ = [
    "ProgramMemory", "MemoryTracker", "HeadroomModel",
    "analyze", "hlo_peak_liveness", "shape_bytes", "nbytes_of",
    "classify", "tracker", "top_live_buffers", "live_array_bytes",
    "is_oom", "maybe_oom_error", "what_if", "default_budget",
    "records", "latest_record", "reset", "memory_report", "bench_summary",
    "crash_section", "build_smoke", "on_compile", "on_run",
    "per_shard_param_bytes",
]


def per_shard_param_bytes(program, scope=None):
    """Per-device parameter bytes under the program's mesh, with the
    per-axis breakdown (`by_axes`: "replicated" / "fsdp" / "fsdp+tp" /
    ...) the sharding planner's byte validation pins against
    (parallel.planner.validate_plan_bytes, <= 1% — a hard test failure
    on drift). Thin delegation to parallel.per_shard_param_bytes; lives
    here too because memory accounting callers reach for memory.py
    first."""
    from .parallel import per_shard_param_bytes as _impl
    return _impl(program, scope)

GiB = 1 << 30

flags.define("memory_analysis", True,
             "capture Compiled.memory_analysis() + an HLO peak-liveness "
             "walk after each block's first jit compile (memory.on_compile; "
             "live-read, 0 disables the extra AOT lower/compile)")
flags.define("memory_tracker", True,
             "sample device.memory_stats()/jax.live_arrays() per "
             "Executor.run into hbm_* gauges (memory.MemoryTracker; "
             "live-read)")
flags.define("hbm_budget_bytes", 0,
             "HBM budget for what-if headroom estimates on backends whose "
             "memory_stats() reports no bytes_limit (0 = 16 GiB default)")


# ---------------------------------------------------------------------------
# Shape/byte helpers
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "u2": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e5m2": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "tf32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_TOKEN = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Bytes of an HLO shape string — 'f32[128,13]{1,0}' -> 6656; tuple
    shapes '(f32[8], s32[])' sum their elements; unknown element types
    (token, opaque) count zero."""
    total = 0
    for dt, dims in _SHAPE_TOKEN.findall(shape_str):
        isz = _DTYPE_BYTES.get(dt)
        if isz is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += isz * n
    return total


def nbytes_of(value) -> int:
    """Bytes of an array-like from shape/dtype metadata ONLY — never reads
    the data, so donated (deleted) jax arrays and ShapeDtypeStructs are
    safe to measure."""
    if value is None:
        return 0
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is None or dtype is None:
        arr = getattr(value, "array", None)
        if callable(arr):          # LoDTensor
            return nbytes_of(arr())
        try:
            a = np.asarray(value)
        except Exception:
            return 0
        shape, dtype = a.shape, a.dtype
    try:
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# HLO peak-liveness walk
# ---------------------------------------------------------------------------

_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)"
    r"\s+(?P<op>[\w\-]+)")
# ops that alias rather than allocate: their "output" is a view/pointer
_ZERO_COST_OPS = frozenset({"bitcast", "get-tuple-element", "tuple",
                            "bitcast-convert"})


def _entry_lines(hlo_text: str) -> List[str]:
    out: List[str] = []
    in_entry = False
    for line in hlo_text.splitlines():
        s = line.strip()
        if not in_entry:
            if s.startswith("ENTRY"):
                in_entry = True
            continue
        if s.startswith("}"):
            break
        out.append(line)
    return out


def hlo_peak_liveness(hlo_text: str, top_k: int = 8) -> Optional[Dict]:
    """Walk the scheduled entry computation (Compiled.as_text() emits
    is_scheduled=true, so instruction order IS the schedule), assign each
    instruction's output buffer a [def, last-use] live range, and report
    the position and composition of the liveness high-water mark — an
    estimate of XLA buffer assignment, not a reimplementation (fusion
    internals and layout padding are invisible at this level). Each peak
    buffer is attributed back to the IR op whose pd.<type> named scope
    emitted it (xplane.hlo_op_names), so the answer reads 'conv2d output,
    not %fusion.42'."""
    from . import xplane

    lines = _entry_lines(hlo_text)
    names: List[str] = []
    sizes: Dict[str, int] = {}
    defpos: Dict[str, int] = {}
    opcode: Dict[str, str] = {}
    params: List[str] = []
    uses_by_pos: List[List[str]] = []
    for line in lines:
        m = _INSTR.match(line)
        if not m:
            continue
        name, shape, op = m.group("name"), m.group("shape"), m.group("op")
        pos = len(names)
        names.append(name)
        defpos[name] = pos
        opcode[name] = op
        sizes[name] = 0 if op in _ZERO_COST_OPS else shape_bytes(shape)
        if op == "parameter":
            params.append(name)
        rhs = line.split("=", 1)[1]
        uses_by_pos.append([t for t in re.findall(r"%([\w.\-]+)", rhs)
                            if t != name])
    n = len(names)
    if n == 0:
        return None

    last_use = {nm: defpos[nm] for nm in names}
    known = set(names)
    for pos, uses in enumerate(uses_by_pos):
        for u in uses:
            if u in known:
                last_use[u] = max(last_use[u], pos)
    # argument buffers exist for the whole execution (XLA cannot free a
    # caller-owned input) and the ROOT buffer is the output — pin to end
    for nm in params:
        last_use[nm] = n - 1
    last_use[names[-1]] = n - 1

    delta = [0] * (n + 1)
    for nm in names:
        b = sizes[nm]
        if not b:
            continue
        delta[defpos[nm]] += b
        delta[last_use[nm] + 1] -= b
    running = 0
    peak = 0
    peak_pos = 0
    for pos in range(n):
        running += delta[pos]
        if running > peak:
            peak, peak_pos = running, pos
    live = [nm for nm in names
            if sizes[nm] and defpos[nm] <= peak_pos <= last_use[nm]]
    live.sort(key=lambda nm: -sizes[nm])
    ir_ops = xplane.hlo_op_names(hlo_text)
    top = [{"instruction": nm, "bytes": sizes[nm],
            "op": ir_ops.get(nm, opcode[nm])}
           for nm in live[:top_k]]
    return {"peak_bytes": peak, "peak_pos": peak_pos,
            "n_instructions": n, "live_at_peak": len(live), "top": top}


# ---------------------------------------------------------------------------
# Static analysis records
# ---------------------------------------------------------------------------

class ProgramMemory:
    """One compiled block's static memory footprint
    (Compiled.memory_analysis() + the liveness walk + donation audit)."""

    __slots__ = ("program", "place", "signature", "argument_bytes",
                 "output_bytes", "temp_bytes", "alias_bytes",
                 "generated_code_bytes", "donated_bytes",
                 "donation_lost_bytes", "peak")

    def __init__(self, program="?", place="?", signature=None):
        self.program = program
        self.place = place
        self.signature = signature
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.alias_bytes = 0
        self.generated_code_bytes = 0
        self.donated_bytes = 0
        self.donation_lost_bytes = 0
        self.peak: Optional[Dict] = None

    @property
    def total_bytes(self) -> int:
        """Static HBM high-water estimate: arguments + (non-aliased)
        outputs + XLA temporaries + executable code."""
        return (self.argument_bytes + self.output_bytes - self.alias_bytes
                + self.temp_bytes + self.generated_code_bytes)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "program": self.program, "place": self.place,
            "signature": ([list(s) for s in self.signature]
                          if self.signature else None),
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "donated_bytes": self.donated_bytes,
            "donation_lost_bytes": self.donation_lost_bytes,
            "total_bytes": self.total_bytes,
            "peak": self.peak,
        }


_LOCK = threading.Lock()
_RECORDS: Dict[str, ProgramMemory] = {}   # prog_label -> latest record
_MAX_RECORDS = 256
_DONATION_WARNED = False


def _remember(rec: ProgramMemory):
    with _LOCK:
        _RECORDS[rec.program] = rec
        while len(_RECORDS) > _MAX_RECORDS:
            _RECORDS.pop(next(iter(_RECORDS)))


def records() -> List[ProgramMemory]:
    with _LOCK:
        return list(_RECORDS.values())


def latest_record(prog_label: str) -> Optional[ProgramMemory]:
    with _LOCK:
        return _RECORDS.get(prog_label)


def analyze(fn, feed_vals, state_vals, rng_counter=0, *, program="?",
            place="?", signature=None, top_k: int = 8) -> ProgramMemory:
    """AOT-lower the jitted block fn from avals (shapes/dtypes only — the
    _hlo_supplier discipline: donated state buffers must never be kept
    alive by the capture) and read XLA's CompiledMemoryStats plus the
    scheduled-HLO liveness walk. A real recompile unless the persistent
    compilation cache covers it."""
    import jax

    def _aval(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            arr = np.asarray(x)
            shape, dtype = arr.shape, arr.dtype
        return jax.ShapeDtypeStruct(shape, dtype)

    avals = jax.tree_util.tree_map(
        _aval, (feed_vals, state_vals, np.uint32(rng_counter)))
    with warnings.catch_warnings():
        # backends without donation support (CPU) warn per compile; the
        # executor's jit call already surfaced it once — the audit below
        # reports the loss in bytes instead
        warnings.filterwarnings("ignore", message=".*donated buffers.*")
        compiled = fn.lower(*avals).compile()
    stats = compiled.memory_analysis()

    rec = ProgramMemory(program=program, place=place, signature=signature)
    rec.argument_bytes = int(getattr(stats, "argument_size_in_bytes", 0))
    rec.output_bytes = int(getattr(stats, "output_size_in_bytes", 0))
    rec.temp_bytes = int(getattr(stats, "temp_size_in_bytes", 0))
    rec.alias_bytes = int(getattr(stats, "alias_size_in_bytes", 0))
    rec.generated_code_bytes = int(
        getattr(stats, "generated_code_size_in_bytes", 0))
    rec.donated_bytes = sum(
        nbytes_of(v) for v in jax.tree_util.tree_leaves(state_vals))
    rec.donation_lost_bytes = max(rec.donated_bytes - rec.alias_bytes, 0)
    try:
        rec.peak = hlo_peak_liveness(compiled.as_text(), top_k=top_k)
    except Exception:
        rec.peak = None
    _remember(rec)
    return rec


def _publish(rec: ProgramMemory):
    """Record -> memory_*_bytes gauges + one memory_analysis step event."""
    for field, value in (
            ("argument", rec.argument_bytes), ("output", rec.output_bytes),
            ("temp", rec.temp_bytes), ("alias", rec.alias_bytes),
            ("generated_code", rec.generated_code_bytes),
            ("donated", rec.donated_bytes),
            ("donation_lost", rec.donation_lost_bytes),
            ("total", rec.total_bytes)):
        telemetry.gauge(
            f"memory_{field}_bytes",
            f"static memory analysis: {field} bytes of the compiled block",
            labels=("program",)).labels(program=rec.program).set(value)
    telemetry.log_event(
        "memory_analysis", program=rec.program, place=rec.place,
        argument_bytes=rec.argument_bytes, output_bytes=rec.output_bytes,
        temp_bytes=rec.temp_bytes, alias_bytes=rec.alias_bytes,
        generated_code_bytes=rec.generated_code_bytes,
        donation_lost_bytes=rec.donation_lost_bytes,
        total_bytes=rec.total_bytes,
        peak_bytes=(rec.peak or {}).get("peak_bytes"))


def _audit_donation(rec: ProgramMemory):
    """Donation audit: donated state the backend did NOT alias in
    memory_analysis() means the optimizer update copies instead of
    reusing HBM in place — double the parameter footprint. Counted per
    program; warned once per process (CPU backends never alias, and a
    test suite full of small programs must not drown in warnings)."""
    global _DONATION_WARNED
    if not rec.donated_bytes or rec.donation_lost_bytes <= 0:
        return
    telemetry.counter(
        "donation_fallback_total",
        "compiles where donated buffers were not aliased in-place by XLA",
        labels=("program",)).labels(program=rec.program).inc()
    if not _DONATION_WARNED:
        _DONATION_WARNED = True
        warnings.warn(
            f"paddle_tpu memory: {_fmt_bytes(rec.donation_lost_bytes)} of "
            f"{_fmt_bytes(rec.donated_bytes)} donated state in program "
            f"'{rec.program}' was not aliased by XLA "
            f"(memory_analysis alias={_fmt_bytes(rec.alias_bytes)}); "
            f"updates will copy instead of reusing HBM in place. Expected "
            f"on CPU backends (no donation support); on TPU check for "
            f"dtype/sharding mismatches between a parameter and its "
            f"update. [warned once; see donation_fallback_total]",
            RuntimeWarning, stacklevel=3)


# ---------------------------------------------------------------------------
# Live accounting
# ---------------------------------------------------------------------------

def live_array_bytes(device=None) -> int:
    """Sum of jax.live_arrays() nbytes (optionally restricted to one
    device) — the CPU-backend fallback for device.memory_stats()."""
    import jax
    total = 0
    try:
        arrs = jax.live_arrays()
    except Exception:
        return 0
    for a in arrs:
        try:
            if device is not None and device not in a.devices():
                continue
            total += int(a.nbytes)
        except Exception:
            continue
    return total


def top_live_buffers(limit: int = 10,
                     names_by_id: Optional[Dict[int, str]] = None
                     ) -> List[Dict[str, Any]]:
    """Largest live device buffers, named when the caller can map array
    identity back to scope/feed variable names (OOM forensics)."""
    import jax
    try:
        arrs = jax.live_arrays()
    except Exception:
        return []
    rows = []
    for a in arrs:
        try:
            rows.append({"nbytes": int(a.nbytes),
                         "shape": [int(d) for d in a.shape],
                         "dtype": str(a.dtype),
                         "name": (names_by_id or {}).get(id(a))})
        except Exception:
            continue
    rows.sort(key=lambda r: -r["nbytes"])
    return rows[:limit]


_CLASS_CACHE: Dict[Tuple, Tuple] = {}


def classify(program, state_vals: Dict[str, Any],
             feed_vals: Dict[str, Any]) -> Dict[str, int]:
    """Split a run's inputs into params / opt_state / feeds bytes by scope
    metadata: parameters are the block's Parameter vars, every other
    persistable state (optimizer accumulators like `<param>_velocity_*`,
    LR vars, BN stats) is opt_state. Byte counts come from avals only, so
    donated arrays are safe to classify after the step ran.

    Bytes are PER-DEVICE: vars sharded under the program's mesh (row-
    sharded embedding tables and their accumulators, tensor/ZeRO-sharded
    params) divide by their shard factor
    (parallel/embedding.state_shard_factor), so the hbm_class_bytes
    breakdown and HeadroomModel inputs describe what one device actually
    holds — the number that OOMs."""
    key = (id(program), getattr(program, "_version", 0))
    hit = _CLASS_CACHE.get(key)
    if hit is None or hit[0] is not program:
        params = {p.name for p in program.global_block().all_parameters()}
        factors: Dict[str, int] = {}
        if getattr(program, "_mesh", None) is not None and (
                getattr(program, "_param_shardings", None)
                or getattr(program, "_sharded_tables", None)):
            from .parallel import embedding as embedding_mod
            for n in state_vals:
                f = embedding_mod.state_shard_factor(program, n)
                if f > 1:
                    factors[n] = f
        _CLASS_CACHE[key] = (program, params, factors)
        while len(_CLASS_CACHE) > 64:
            _CLASS_CACHE.pop(next(iter(_CLASS_CACHE)))
        hit = _CLASS_CACHE[key]
    params, factors = hit[1], hit[2]
    out = {"params": 0, "opt_state": 0, "feeds": 0}
    for n, v in state_vals.items():
        b = nbytes_of(v)
        f = factors.get(n, 1)
        if f > 1:
            b = -(-b // f)   # ceil: XLA pads uneven shards
        out["params" if n in params else "opt_state"] += b
    for v in feed_vals.values():
        out["feeds"] += nbytes_of(v)
    return out


class MemoryTracker:
    """Per-run HBM sampler. On TPU `device.memory_stats()` reports the
    allocator's truth (bytes_in_use / peak_bytes_in_use / bytes_limit);
    CPU backends return None and the tracker falls back to summing
    jax.live_arrays(). Feeds the hbm_* gauges and keeps a process-lifetime
    peak for bench/OOM reports."""

    def __init__(self):
        self.peak_bytes = 0
        self.last: Dict[str, Any] = {}

    def sample(self, device=None, program: Optional[str] = None,
               classes: Optional[Dict[str, int]] = None) -> Dict[str, Any]:
        stats = None
        if device is not None:
            try:
                stats = device.memory_stats()
            except Exception:
                stats = None
        if stats:
            in_use = int(stats.get("bytes_in_use", 0) or 0)
            limit = int(stats.get("bytes_limit", 0) or 0)
            dev_peak = int(stats.get("peak_bytes_in_use", in_use) or in_use)
            source = "device"
        else:
            in_use = live_array_bytes(device)
            limit = int(flags.get("hbm_budget_bytes") or 0)
            dev_peak = in_use
            source = "live_arrays"
        self.peak_bytes = max(self.peak_bytes, dev_peak, in_use)
        label = str(device) if device is not None else "?"
        telemetry.gauge(
            "hbm_bytes_in_use", "device memory in use after the last run "
            "(memory_stats, or live-array sum on backends without stats)",
            labels=("device",)).labels(device=label).set(in_use)
        telemetry.gauge(
            "hbm_peak_bytes", "high-water device memory across the process",
            labels=("device",)).labels(device=label).set(self.peak_bytes)
        if limit:
            telemetry.gauge(
                "hbm_limit_bytes", "device memory capacity (bytes_limit, "
                "or the hbm_budget_bytes flag)",
                labels=("device",)).labels(device=label).set(limit)
        cls = dict(classes or {})
        if classes is not None:
            cls["activations"] = max(in_use - sum(classes.values()), 0)
            for kind, v in cls.items():
                telemetry.gauge(
                    "hbm_class_bytes",
                    "live bytes by class: params/opt_state/feeds/activations",
                    labels=("device", "kind")).labels(
                        device=label, kind=kind).set(v)
        self.last = {"device": label, "source": source, "program": program,
                     "bytes_in_use": in_use, "peak_bytes": self.peak_bytes,
                     "limit_bytes": limit, "classes": cls}
        return self.last


_TRACKER = MemoryTracker()


def tracker() -> MemoryTracker:
    return _TRACKER


def reset():
    """Forget records and tracker state (test isolation)."""
    global _DONATION_WARNED
    with _LOCK:
        _RECORDS.clear()
    _CLASS_CACHE.clear()
    _TRACKER.peak_bytes = 0
    _TRACKER.last = {}
    _DONATION_WARNED = False


# ---------------------------------------------------------------------------
# Executor hooks
# ---------------------------------------------------------------------------

def on_compile(exe, compiled, program, prog_label, place_label,
               feed_vals, state_vals, rng_counter,
               signature=None) -> Optional[ProgramMemory]:
    """Executor hook after a block's first jit compile: static analysis +
    gauges + donation audit. Gated on the live memory_analysis flag."""
    if not flags.get("memory_analysis"):
        return None
    rec = analyze(compiled.fn, feed_vals, state_vals, rng_counter,
                  program=prog_label, place=place_label,
                  signature=signature)
    _publish(rec)
    _audit_donation(rec)
    return rec


def on_run(exe, program, prog_label, feed_vals,
           state_vals) -> Optional[Dict[str, Any]]:
    """Executor hook after every run: one tracker sample. Gated on the
    live memory_tracker flag."""
    if not flags.get("memory_tracker"):
        return None
    classes = None
    try:
        classes = classify(program, state_vals, feed_vals)
    except Exception:
        pass
    return _TRACKER.sample(device=getattr(exe, "device", None),
                           program=prog_label, classes=classes)


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_OOM_PAT = re.compile(r"RESOURCE_EXHAUSTED|[Oo]ut of memory|"
                      r"[Aa]llocation .* exceeds|OOM when allocating")


def is_oom(exc: BaseException) -> bool:
    """Does this look like the runtime ran out of device memory? jax
    surfaces XLA's RESOURCE_EXHAUSTED status as XlaRuntimeError with the
    status name in the message — string-matched here because the
    exception type itself is backend-private."""
    return bool(_OOM_PAT.search(str(exc)))


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.2f}{unit}"
        n /= 1024.0
    return f"{n:.2f}TiB"


def maybe_oom_error(exe, program, prog_label, exc, feed_vals=None,
                    state_vals=None):
    """If `exc` is a raw backend OOM, build the structured errors.OOMError
    that should replace it (carrying breakdown, top live buffers, donation
    losses and suggestions); otherwise None. Never raises: forensics that
    fail must not mask the original error."""
    from .errors import OOMError
    if isinstance(exc, OOMError) or not is_oom(exc):
        return None
    try:
        return _build_oom_error(exe, program, prog_label, exc,
                                feed_vals or {}, state_vals or {})
    except Exception:
        return None


def _build_oom_error(exe, program, prog_label, exc, feed_vals, state_vals):
    from .errors import OOMError
    telemetry.counter(
        "oom_errors_total", "device OOMs surfaced as errors.OOMError",
        labels=("program",)).labels(program=prog_label).inc()

    breakdown: Dict[str, Any] = {}
    try:
        breakdown.update(classify(program, state_vals, feed_vals))
    except Exception:
        pass
    device = getattr(exe, "device", None)
    stats = None
    if device is not None:
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
    if stats:
        breakdown["bytes_in_use"] = int(stats.get("bytes_in_use", 0) or 0)
        breakdown["bytes_limit"] = int(stats.get("bytes_limit", 0) or 0)
        breakdown["peak_bytes_in_use"] = int(
            stats.get("peak_bytes_in_use", 0) or 0)
    else:
        breakdown["bytes_in_use"] = live_array_bytes(device)

    rec = latest_record(prog_label)
    names_by_id = {}
    for n, v in list(state_vals.items()) + list(feed_vals.items()):
        try:
            names_by_id[id(v)] = n
        except Exception:
            pass
    top = top_live_buffers(10, names_by_id)

    suggestions: List[str] = []
    lost = rec.donation_lost_bytes if rec else 0
    if lost:
        suggestions.append(
            f"{_fmt_bytes(lost)} of donated state was not aliased by XLA "
            f"(donation fallback doubles the parameter footprint) — see "
            f"donation_fallback_total and the compile-time warning")
    if getattr(program, "_amp_dtype", None) is None:
        suggestions.append(
            "enable mixed precision (amp.decorate, level O2) to roughly "
            "halve parameter/activation bytes")
    if rec is not None and rec.temp_bytes > max(rec.argument_bytes, 1):
        suggestions.append(
            f"XLA temporaries dominate ({_fmt_bytes(rec.temp_bytes)} temp "
            f"vs {_fmt_bytes(rec.argument_bytes)} arguments) — "
            f"rematerialize activations or shard the model "
            f"(parallel.shard_all_params_zero)")
    suggestions.append(
        "reduce the batch size — `python -m paddle_tpu memory --what-if` "
        "predicts the largest batch that fits")

    lines = [f"out of device memory running program '{prog_label}'",
             f"  backend error: {str(exc).splitlines()[0][:300]}"]
    cls = {k: v for k, v in breakdown.items()
           if k in ("params", "opt_state", "feeds")}
    if cls:
        lines.append("  live breakdown: " + ", ".join(
            f"{k}={_fmt_bytes(v)}" for k, v in cls.items()))
    if rec is not None:
        lines.append(
            f"  static analysis: args={_fmt_bytes(rec.argument_bytes)} "
            f"out={_fmt_bytes(rec.output_bytes)} "
            f"temp={_fmt_bytes(rec.temp_bytes)} "
            f"total={_fmt_bytes(rec.total_bytes)}")
    for s in suggestions:
        lines.append(f"  suggestion: {s}")
    # keep the status name in the message so callers matching the raw
    # XlaRuntimeError text (retry loops, bench transient markers) still do
    lines.append("  (RESOURCE_EXHAUSTED)")
    return OOMError("\n".join(lines), program=prog_label,
                    breakdown=breakdown, top_buffers=top,
                    donation_lost_bytes=lost,
                    analysis=rec.to_dict() if rec else None,
                    suggestions=suggestions,
                    device=str(device) if device is not None else None)


# ---------------------------------------------------------------------------
# What-if headroom estimation
# ---------------------------------------------------------------------------

class HeadroomModel:
    """peak(b) = fixed_bytes + per_item_bytes * b, least-squares fit from
    static analyses at >= 2 batch sizes. Linear in the batch because every
    per-sample buffer (feeds, activations, logits) scales with b while
    params/opt-state/code do not; XLA padding and fusion keep it only
    approximately linear — which is why what_if() validates the
    extrapolation against a fresh analysis at the predicted batch.

    For sharded programs both inputs are per-device numbers: the static
    analyses XLA returns for an SPMD module are post-partitioning, and
    classify() divides sharded state (row-sharded embedding tables and
    their optimizer accumulators included) by its shard factor — so
    fixed_bytes carries the per-shard table + opt-state footprint and
    max_batch() answers against one device's budget, the one that OOMs."""

    def __init__(self, fixed_bytes: float, per_item_bytes: float,
                 points: Optional[Sequence[Tuple[int, int]]] = None):
        self.fixed_bytes = float(fixed_bytes)
        self.per_item_bytes = float(per_item_bytes)
        self.points = [(int(b), int(y)) for b, y in (points or [])]

    @classmethod
    def fit(cls, points: Sequence[Tuple[int, int]]) -> "HeadroomModel":
        pts = sorted({(int(b), int(y)) for b, y in points})
        if len({b for b, _ in pts}) < 2:
            raise ValueError("HeadroomModel.fit needs analyses at >= 2 "
                             "distinct batch sizes")
        xs = [b for b, _ in pts]
        ys = [y for _, y in pts]
        mx = sum(xs) / len(xs)
        my = sum(ys) / len(ys)
        var = sum((x - mx) ** 2 for x in xs)
        slope = sum((x - mx) * (y - my) for x, y in pts) / var
        slope = max(slope, 0.0)
        fixed = max(my - slope * mx, 0.0)
        return cls(fixed, slope, pts)

    def predict(self, batch: int) -> int:
        return int(round(self.fixed_bytes + self.per_item_bytes * batch))

    def max_batch(self, budget_bytes: int) -> Optional[int]:
        """Largest batch fitting the budget; None when the footprint does
        not grow with the batch (nothing to bound)."""
        if self.per_item_bytes <= 0:
            return None
        if budget_bytes <= self.fixed_bytes:
            return 0
        return int((budget_bytes - self.fixed_bytes) // self.per_item_bytes)

    def headroom(self, budget_bytes: int, batch: int) -> int:
        """Device bytes left under `budget_bytes` after the predicted
        peak at `batch` — what's genuinely free for extra resident state.
        The beyond-HBM embedding cache sizes its hot-row slab from this
        (emb_cache.budget_from_headroom subtracts the window feed buffer
        on top). Clamped at 0: an over-budget batch has no headroom."""
        return max(0, int(budget_bytes) - self.predict(batch))

    def to_dict(self) -> Dict[str, Any]:
        return {"fixed_bytes": int(self.fixed_bytes),
                "per_item_bytes": round(self.per_item_bytes, 2),
                "points": self.points}


def default_budget(device=None) -> int:
    """HBM budget for headroom estimates: the device's bytes_limit when
    memory_stats reports one, else the hbm_budget_bytes flag, else 16 GiB
    (a v5e-class chip)."""
    if device is not None:
        try:
            stats = device.memory_stats()
            if stats and stats.get("bytes_limit"):
                return int(stats["bytes_limit"])
        except Exception:
            pass
    v = int(flags.get("hbm_budget_bytes") or 0)
    return v if v > 0 else 16 * GiB


def what_if(measure: Callable[[int], ProgramMemory],
            batches: Sequence[int] = (8, 32),
            budget_bytes: Optional[int] = None,
            validate: bool = True,
            max_validate_batch: Optional[int] = None) -> Dict[str, Any]:
    """'Will batch B fit?' — fit a HeadroomModel from static analyses at
    `batches`, predict the max batch under `budget_bytes`, then validate
    the model by re-analyzing AT the predicted batch (a fresh XLA
    compile, independent of the straight-line extrapolation) and
    reporting the relative error. `measure(b)` must return the
    ProgramMemory of the program compiled at batch b — e.g. a closure
    over Executor.static_memory_analysis."""
    points = []
    for b in batches:
        points.append((int(b), measure(int(b)).total_bytes))
    model = HeadroomModel.fit(points)
    budget = int(budget_bytes) if budget_bytes else default_budget()
    bmax = model.max_batch(budget)
    out: Dict[str, Any] = {"model": model.to_dict(),
                           "budget_bytes": budget, "max_batch": bmax,
                           "points": points}
    if validate and bmax:
        vb = bmax if max_validate_batch is None else min(
            bmax, int(max_validate_batch))
        measured = measure(vb).total_bytes
        predicted = model.predict(vb)
        out["validate_batch"] = vb
        out["predicted_bytes"] = predicted
        out["measured_bytes"] = measured
        out["rel_err"] = abs(predicted - measured) / max(measured, 1)
    return out


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------

def memory_report() -> Dict[str, Any]:
    """One JSON-able view of everything this module knows (CLI summary)."""
    return {"programs": [r.to_dict() for r in records()],
            "tracker": dict(_TRACKER.last),
            "peak_bytes": _TRACKER.peak_bytes}


def bench_summary() -> Optional[Dict[str, Any]]:
    """peak_hbm_bytes (+ hbm_utilization when a capacity is known) for the
    bench JSON record; None when nothing was measured."""
    peak = _TRACKER.peak_bytes
    limit = int(_TRACKER.last.get("limit_bytes") or 0) if _TRACKER.last else 0
    if not peak:
        peak = max((r.total_bytes for r in records()), default=0)
    if not peak:
        return None
    out: Dict[str, Any] = {"peak_hbm_bytes": int(peak),
                           "hbm_utilization": None}
    if limit:
        out["hbm_utilization"] = round(peak / limit, 4)
    return out


def crash_section() -> Dict[str, Any]:
    """The 'memory' section of an inspector crash report."""
    return {"tracker": dict(_TRACKER.last),
            "peak_bytes": _TRACKER.peak_bytes,
            "programs": [r.to_dict() for r in records()[-8:]],
            "live_buffers": top_live_buffers(5)}


# ---------------------------------------------------------------------------
# Smoke programs (memory CLI + tests)
# ---------------------------------------------------------------------------

def build_smoke(name: str) -> Dict[str, Any]:
    """Build one of the named smoke programs for memory measurements:
    'fit_a_line' (13->1 linear regression, SGD) or 'resnet' (CIFAR-shaped
    ResNet classifier, Momentum). Returns {main, startup, loss, feed_fn,
    data_fn, label}: feed_fn(b) yields aval-only feeds (ShapeDtypeStructs,
    safe at any batch — static analysis never materializes them),
    data_fn(b) yields real zero arrays for executed steps."""
    import jax
    import paddle_tpu as fluid
    from .framework import unique_name

    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            if name == "fit_a_line":
                x = fluid.layers.data(name="x", shape=[13], dtype="float32")
                y = fluid.layers.data(name="y", shape=[1], dtype="float32")
                pred = fluid.layers.fc(input=x, size=1, act=None)
                cost = fluid.layers.square_error_cost(input=pred, label=y)
                loss = fluid.layers.mean(cost)
                fluid.optimizer.SGD(learning_rate=0.01).minimize(
                    loss, startup_program=startup)
                feeds = {"x": ((13,), np.float32), "y": ((1,), np.float32)}
            elif name == "resnet":
                from . import models
                img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                        dtype="float32")
                label = fluid.layers.data(name="label", shape=[1],
                                          dtype="int64")
                loss, _, _ = models.build_image_classifier(
                    models.resnet_cifar10, img, label, class_dim=10,
                    depth=20)
                fluid.optimizer.Momentum(
                    learning_rate=0.001, momentum=0.9).minimize(
                        loss, startup_program=startup)
                feeds = {"img": ((3, 32, 32), np.float32),
                         "label": ((1,), np.int64)}
            else:
                raise ValueError(f"unknown smoke program '{name}' "
                                 f"(known: fit_a_line, resnet)")

    def feed_fn(batch: int):
        return {n: jax.ShapeDtypeStruct((batch,) + shape, dtype)
                for n, (shape, dtype) in feeds.items()}

    def data_fn(batch: int):
        return {n: np.zeros((batch,) + shape, dtype)
                for n, (shape, dtype) in feeds.items()}

    return {"main": main, "startup": startup, "loss": loss,
            "feed_fn": feed_fn, "data_fn": data_fn, "label": name}
