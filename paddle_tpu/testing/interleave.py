"""Deterministic interleaving harness: seeded cooperative scheduling.

CHESS/loom-style systematic schedule exploration, specialized to
CPython: a `sys.settrace`-based cooperative scheduler that serializes
the watched threads and forces a preemption decision at every traced
line — i.e. at every shared-state access point inside the watched
files. The schedule is drawn from `random.Random(seed)`, so

  * a failing interleaving is **replayable**: re-running with the
    recorded seed yields the same schedule and the same failure;
  * `explore()` sweeps seeds until an invariant breaks, turning
    "this race fires once in a thousand runs under load" into "seed 17
    fails, every time".

How it works
------------
`run_interleaved(workers, seed=...)` starts one thread per worker.
Each thread installs a trace function whose 'line' events call back
into the scheduler (`_checkpoint`): the thread parks and waits for the
scheduler's grant. Exactly one thread runs between checkpoints; at
each checkpoint the scheduler picks the next runnable thread with the
seeded RNG. Threads the *subsystem under test* spawns are adopted via
`threading.settrace` the moment they execute a watched line, so real
pipeline/batcher/prefetch threads participate in the schedule too.

A thread that blocks in a real primitive (lock, queue, join) while
holding the grant cannot park; after a short grace period the
scheduler *detaches* it — it runs free (the OS scheduler interleaves
it) until its next watched line, where it re-attaches. This keeps the
harness deadlock-free over code that genuinely blocks, at the cost of
a bounded nondeterminism window; drives that want bit-exact replay
(e.g. the planted `DropCountFixture`) use spin-waits over plain lists
so every wait is itself a traced checkpoint.

Watched files default to the files defining the worker callables;
pass `watch=[module_or_path, ...]` to trace a subsystem's internals
(e.g. `watch=[paddle_tpu.reader.pipeline]`).

The planted fixture
-------------------
`DropCountFixture` reproduces the PR 17 drop-count race class (see
reader/pipeline.py `_produce_windows`: the end-of-pass drop count must
ride the stop marker so the consumer books it; the pre-fix builder
published the stop marker first and counted after, so a fast consumer
read 0). `buggy=True` plants that exact ordering; the harness is
required to find a seed that observes the lost count, and to
reproduce it deterministically from that seed. `buggy=False` is the
shipped ordering and survives every seed.
"""

from __future__ import annotations

import random
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field
from types import ModuleType
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "DropCountFixture",
    "InterleaveResult",
    "explore",
    "run_interleaved",
]


# ---------------------------------------------------------------------------
# result object
# ---------------------------------------------------------------------------

@dataclass
class InterleaveResult:
    """Outcome of one scheduled run."""
    seed: int
    #: executed schedule: (thread name, "file:line") per granted step
    schedule: List[Tuple[str, str]] = field(default_factory=list)
    #: per-thread exception (worker body raised), by thread name
    errors: Dict[str, BaseException] = field(default_factory=dict)
    steps: int = 0
    #: True when max_steps fired and the tail ran unscheduled
    truncated: bool = False
    #: threads still alive at the overall deadline (name -> stack text)
    stuck: Dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.errors and not self.stuck

    def first_error(self) -> Optional[BaseException]:
        for name in sorted(self.errors):
            return self.errors[name]
        return None

    def signature(self) -> Tuple[Tuple[str, str], ...]:
        """Hashable schedule fingerprint for determinism assertions."""
        return tuple(self.schedule)


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------

class _ThreadState:
    __slots__ = ("name", "parked", "finished", "detached", "where",
                 "adopted")

    def __init__(self, name: str, adopted: bool = False):
        self.name = name
        self.parked = False
        self.finished = False
        self.detached = False
        self.where = "?"
        self.adopted = adopted


def _watch_files(workers, watch) -> Tuple[str, ...]:
    files = []
    for _, fn in workers:
        code = getattr(fn, "__code__", None)
        if code is not None:
            files.append(code.co_filename)
    for w in watch or ():
        if isinstance(w, ModuleType):
            f = getattr(w, "__file__", None)
            if f:
                files.append(f)
        else:
            files.append(str(w))
    return tuple(dict.fromkeys(files))


class _Scheduler:
    def __init__(self, workers, seed: int, watch, max_steps: int,
                 grace_s: float, deadline_s: float,
                 sticky: float = 0.9):
        self.rng = random.Random(seed)
        # probability of NOT preempting the running thread at a
        # checkpoint. Most ordering bugs need only one or two
        # preemptions placed exactly right (the CHESS observation), so
        # long runs with rare, randomly-placed switches find them far
        # faster than a uniform coin flip per line
        self.sticky = sticky
        self.result = InterleaveResult(seed=seed)
        self.max_steps = max_steps
        self.grace_s = grace_s
        self.deadline_s = deadline_s
        self.watch = _watch_files(workers, watch)
        self._cv = threading.Condition()
        self._threads: Dict[int, _ThreadState] = {}
        self._grant: Optional[int] = None
        self._released = False
        self._adopt_seq = 0
        self._workers = workers
        # the harness's own machinery runs on the worker threads inside
        # a watched file — it must never checkpoint (a thread parked
        # mid-registration would deadlock the startup barrier)
        self._own_code = {
            type(self)._bootstrap.__code__,
            _ThreadState.__init__.__code__,
        }

    # -- trace plumbing (runs on the worker threads) --

    def _global_trace(self, frame, event, arg):
        if event != "call":
            return None
        if frame.f_code in self._own_code:
            return None
        fname = frame.f_code.co_filename
        for w in self.watch:
            if fname.endswith(w) or w in fname:
                return self._local_trace
        return None

    def _local_trace(self, frame, event, arg):
        if event == "line":
            self._checkpoint(frame)
        return self._local_trace

    def _checkpoint(self, frame):
        tid = threading.get_ident()
        with self._cv:
            if self._released:
                return
            st = self._threads.get(tid)
            if st is None:
                # a thread the subsystem spawned just executed a watched
                # line: adopt it into the schedule
                self._adopt_seq += 1
                st = _ThreadState(
                    threading.current_thread().name
                    or f"adopted-{self._adopt_seq}", adopted=True)
                self._threads[tid] = st
            st.detached = False
            st.parked = True
            st.where = (f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}"
                        f":{frame.f_lineno}")
            self._cv.notify_all()
            while self._grant != tid and not self._released:
                self._cv.wait(0.5)
            if self._released:
                st.parked = False
                return
            self._grant = None
            st.parked = False
            self.result.schedule.append((st.name, st.where))
            self.result.steps += 1
            if self.result.steps >= self.max_steps:
                self.result.truncated = True
                self._released = True
                self._cv.notify_all()

    def _bootstrap(self, fn, name):
        tid = threading.get_ident()
        with self._cv:
            # self-registration: the thread is in the schedule before
            # its first traced line can possibly fire
            self._threads[tid] = _ThreadState(name)
            self._cv.notify_all()
        sys.settrace(self._global_trace)
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - reported in result
            with self._cv:
                self.result.errors[self._threads[tid].name] = e
        finally:
            sys.settrace(None)
            with self._cv:
                self._threads[tid].finished = True
                self._threads[tid].parked = False
                self._cv.notify_all()

    # -- the schedule loop (runs on the caller thread) --

    def run(self) -> InterleaveResult:
        threads = []
        prev_threading_trace = getattr(threading, "_trace_hook", None)
        threading.settrace(self._global_trace)
        try:
            for name, fn in self._workers:
                t = threading.Thread(
                    target=self._bootstrap, args=(fn, name), daemon=True,
                    name=f"ilv-{name}")
                t.start()
                threads.append(t)
            self._loop()
        finally:
            threading.settrace(prev_threading_trace)
            with self._cv:
                self._released = True
                self._cv.notify_all()
            for t in threads:
                t.join(timeout=self.deadline_s)
            for t in threads:
                if t.is_alive():
                    frames = sys._current_frames()
                    fr = frames.get(t.ident)
                    self.result.stuck[t.name] = (
                        "".join(traceback.format_stack(fr))
                        if fr is not None else "<no frame>")
        return self.result

    def _loop(self):
        deadline = time.monotonic() + self.deadline_s
        last: Optional[int] = None
        with self._cv:
            # startup barrier: no grant until every worker has
            # registered AND parked at its first checkpoint — otherwise
            # the first decisions see a partial thread set and the
            # schedule depends on OS startup timing instead of the seed
            t0 = time.monotonic()
            while True:
                own = [s for s in self._threads.values()
                       if not s.adopted]
                if len(own) == len(self._workers) and \
                        all(s.parked or s.finished for s in own):
                    break
                if time.monotonic() - t0 > self.deadline_s:
                    break
                self._cv.wait(0.05)
            while not self._released:
                live = [s for s in self._threads.values()
                        if not s.finished]
                own = [s for s in self._threads.values()
                       if not s.adopted and not s.finished]
                if not own:
                    return          # every worker done; adopted run free
                parked = sorted(
                    (tid for tid, s in self._threads.items()
                     if s.parked),
                    key=lambda tid: self._threads[tid].name)
                if not parked:
                    # everything is running or blocked in a real
                    # primitive; wait for someone to park or finish
                    if not self._cv.wait(self.grace_s) and \
                            time.monotonic() > deadline:
                        return      # watchdog: stuck set reported by run()
                    continue
                # sticky choice: keep the last thread running unless the
                # (seeded) coin says preempt; both branches consume RNG
                # deterministically as a function of the history
                if last in parked and len(parked) > 1 and \
                        self.rng.random() < self.sticky:
                    tid = last
                else:
                    tid = parked[self.rng.randrange(len(parked))]
                last = tid
                st = self._threads[tid]
                self._grant = tid
                self._cv.notify_all()
                t0 = time.monotonic()
                while not self._released:
                    # the grant is consumed (the thread cleared it and
                    # unparked) AND the thread is back at a checkpoint
                    # or done: its slice is over, schedule the next one
                    if self._grant != tid and (st.parked or st.finished):
                        break
                    remaining = self.grace_s - (time.monotonic() - t0)
                    if remaining <= 0:
                        # blocked for real (lock/queue/join): detach so
                        # another thread can unblock it; it re-attaches
                        # at its next watched line
                        st.detached = True
                        if self._grant == tid:
                            self._grant = None
                        break
                    self._cv.wait(remaining)
                if time.monotonic() > deadline:
                    return


def run_interleaved(workers: Iterable, *, seed: int = 0,
                    watch: Optional[Iterable] = None,
                    max_steps: int = 20000, grace_s: float = 0.05,
                    deadline_s: float = 20.0,
                    sticky: float = 0.9) -> InterleaveResult:
    """Run `workers` under a seeded cooperative schedule.

    workers: callables, or (name, callable) pairs. watch: extra modules
    or path substrings whose lines become preemption points (defaults
    to the files defining the workers). Returns an InterleaveResult;
    worker exceptions land in result.errors, they do not propagate.
    """
    norm = []
    for i, w in enumerate(workers):
        if isinstance(w, tuple):
            norm.append((str(w[0]), w[1]))
        else:
            norm.append((getattr(w, "__name__", f"w{i}") or f"w{i}", w))
    sched = _Scheduler(norm, seed, watch, max_steps, grace_s, deadline_s,
                       sticky=sticky)
    return sched.run()


def explore(build: Callable[[], Tuple[Iterable, Optional[Callable]]],
            seeds: Iterable[int] = range(32), *,
            stop_at_first: bool = True,
            **run_kw) -> List[Tuple[int, BaseException,
                                    InterleaveResult]]:
    """Sweep seeds until an invariant breaks.

    `build()` returns (workers, check): fresh workers over fresh state,
    plus an optional post-run invariant callable that raises on
    violation. Returns [(seed, error, result), ...] — the recorded seed
    replays the failure via run_interleaved(..., seed=seed).
    """
    failures = []
    for seed in seeds:
        workers, check = build()
        res = run_interleaved(workers, seed=seed, **run_kw)
        err = res.first_error()
        if err is None and check is not None:
            try:
                check()
            except BaseException as e:  # noqa: BLE001 - the point
                err = e
        if err is not None:
            failures.append((seed, err, res))
            if stop_at_first:
                break
    return failures


# ---------------------------------------------------------------------------
# planted fixture: the PR 17 drop-count race class
# ---------------------------------------------------------------------------

class DropCountFixture:
    """Builder/consumer pair planting the drop-count ordering bug.

    The builder ends a pass with `remainder` dropped batches: it must
    make the count visible BEFORE (or atomically with) the stop marker,
    because the consumer books the count at the pull that observes the
    stop. buggy=True publishes the marker first and counts after — the
    planted defect; buggy=False is the shipped ordering.

    All coordination is spin-waiting over plain lists: every wait is a
    traced line, so the schedule (and therefore the failure) is a pure
    function of the seed.
    """

    def __init__(self, buggy: bool = True, remainder: int = 3):
        self.buggy = buggy
        self.remainder = remainder
        self.mailbox: List[object] = []   # the window queue stand-in
        self.dropped = 0                  # the racy counter
        self.observed: Optional[int] = None

    def builder(self):
        self.mailbox.append("window-0")
        if self.buggy:
            self.mailbox.append("STOP")
            self.dropped += self.remainder   # counted AFTER publication
        else:
            self.dropped += self.remainder   # count rides the marker
            self.mailbox.append("STOP")

    def consumer(self):
        taken = 0
        while True:
            while len(self.mailbox) <= taken:
                pass                      # traced spin: a checkpoint
            item = self.mailbox[taken]
            taken += 1
            if item == "STOP":
                self.observed = self.dropped
                return

    def check(self):
        if self.observed != self.remainder:
            raise AssertionError(
                f"drop-count race: consumer booked {self.observed} "
                f"dropped batches at STOP, builder dropped "
                f"{self.remainder}")

    def workers(self):
        return [("builder", self.builder), ("consumer", self.consumer)]
