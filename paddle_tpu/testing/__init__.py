"""Testing utilities: deterministic concurrency harness.

`paddle_tpu.testing.interleave` is the dynamic half of the repo's
thread-safety tooling (the static half is
`paddle_tpu.analysis.threads`): a seeded cooperative scheduler that
forces preemption at shared-state access points so data races become
reproducible test failures instead of one-in-a-thousand flakes."""

from .interleave import (  # noqa: F401
    DropCountFixture,
    InterleaveResult,
    explore,
    run_interleaved,
)

__all__ = [
    "DropCountFixture",
    "InterleaveResult",
    "explore",
    "run_interleaved",
]
