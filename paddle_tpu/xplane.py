"""XSpace/XPlane (.xplane.pb) wire-format parser + HLO->IR-op attribution.

jax.profiler.trace writes xplane protos; the tensorboard profile plugin in
this image can't load them (TF version skew), so this decodes the wire
format directly — only the fields needed to aggregate device-op time:

  XSpace.planes=1 / XPlane{name=2, lines=3, event_metadata=4}
  XLine{name=2, timestamp_ns=3, events=4}
  XEvent{metadata_id=1, offset_ps=2, duration_ps=3}
  XEventMetadata map entry {key=1, value=2} / XEventMetadata{id=1, name=2}

The executor wraps every IR op's lowering in jax.named_scope("pd.<type>")
(executor._exec_op), so the compiled module's per-instruction
`metadata={op_name="jit(fn)/.../pd.<type>/<prim>"}` carries the IR op that
emitted each HLO instruction — including the representative op of each
fusion. `hlo_op_names` extracts that mapping from the optimized HLO text
and `attribute` joins it with the xplane per-instruction timings, giving
the reference ParseEvents-style "which op eats the step" table for the
whole-block jit (reference platform/profiler.h:137-166)."""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, Optional

__all__ = ["aggregate", "aggregate_dir", "aggregate_lines", "hlo_op_names",
           "attribute", "category", "fields", "parse_plane",
           "plane_events", "timeline_dir"]


def _varint(buf, i):
    r = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, i
        shift += 7


def fields(buf):
    """Yield (field_number, wire_type, value) over a serialized message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i: i + ln]
            i += ln
        elif wt == 5:
            v = buf[i: i + 4]
            i += 4
        elif wt == 1:
            v = buf[i: i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


def parse_plane(buf):
    name = ""
    lines = []
    meta = {}
    for fno, wt, v in fields(buf):
        if fno == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            lines.append(v)
        elif fno == 4 and wt == 2:
            k = None
            mname = None
            for f2, w2, v2 in fields(v):
                if f2 == 1 and w2 == 0:
                    k = v2
                elif f2 == 2 and w2 == 2:
                    for f3, w3, v3 in fields(v2):
                        if f3 == 1 and w3 == 0 and k is None:
                            k = v3
                        elif f3 == 2 and w3 == 2:
                            mname = v3.decode("utf-8", "replace")
            if k is not None and mname is not None:
                meta[k] = mname
    return name, lines, meta


def aggregate_lines(path) -> Dict[str, list]:
    """-> {plane_name: [{event_name: total_ps} per XLine]} — per-line
    aggregation so callers can dedup a plane's derived lines (xplane device
    planes repeat each instruction on the raw XLA-op line AND on derived
    step/module/framework-op lines)."""
    buf = open(path, "rb").read()
    out: Dict[str, list] = {}
    for fno, wt, v in fields(buf):
        if fno != 1 or wt != 2:
            continue
        pname, lines, meta = parse_plane(v)
        per_line = out.setdefault(pname, [])
        for line in lines:
            agg: Dict[str, int] = {}
            for f2, w2, v2 in fields(line):
                if f2 != 4 or w2 != 2:   # XLine.events
                    continue
                mid = dur = 0
                for f3, w3, v3 in fields(v2):
                    if f3 == 1 and w3 == 0:
                        mid = v3
                    elif f3 == 3 and w3 == 0:
                        dur = v3
                name = meta.get(mid, f"#{mid}")
                agg[name] = agg.get(name, 0) + dur
            per_line.append(agg)
    return out


def plane_events(path) -> Dict[str, list]:
    """-> {plane_name: [line, ...]} where each line is
    {"name": str, "timestamp_ns": int,
     "events": [(event_name, offset_ps, duration_ps), ...]}.

    The full-resolution view of the same planes `aggregate_lines` sums:
    XLine.timestamp_ns anchors the line on the wall clock and
    XEvent.offset_ps places each event within the line, so
    timestamp_ns*1e3 + offset_ps orders events across lines and planes —
    the timeline the waterfall/duty-cycle analysis needs."""
    buf = open(path, "rb").read()
    out: Dict[str, list] = {}
    for fno, wt, v in fields(buf):
        if fno != 1 or wt != 2:
            continue
        pname, lines, meta = parse_plane(v)
        per_line = out.setdefault(pname, [])
        for line in lines:
            lname = ""
            ts_ns = 0
            events = []
            for f2, w2, v2 in fields(line):
                if f2 == 2 and w2 == 2:      # XLine.name
                    lname = v2.decode("utf-8", "replace")
                elif f2 == 3 and w2 == 0:    # XLine.timestamp_ns
                    ts_ns = v2
                elif f2 == 4 and w2 == 2:    # XLine.events
                    mid = off = dur = 0
                    for f3, w3, v3 in fields(v2):
                        if f3 == 1 and w3 == 0:
                            mid = v3
                        elif f3 == 2 and w3 == 0:
                            off = v3
                        elif f3 == 3 and w3 == 0:
                            dur = v3
                    events.append((meta.get(mid, f"#{mid}"), off, dur))
            per_line.append({"name": lname, "timestamp_ns": ts_ns,
                             "events": events})
    return out


def timeline_dir(trace_dir) -> list:
    """Merge every .xplane.pb under trace_dir into a flat list of
    {"plane", "line", "timestamp_ns", "events"} records (events carry
    (name, offset_ps, duration_ps)), device planes first."""
    records = []
    for p in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True):
        for pname, lines in plane_events(p).items():
            for line in lines:
                records.append({"plane": pname, "line": line["name"],
                                "timestamp_ns": line["timestamp_ns"],
                                "events": line["events"]})
    records.sort(key=lambda r: (not r["plane"].startswith("/device:"),
                                r["plane"], r["timestamp_ns"]))
    return records


def aggregate(path) -> Dict[str, Dict[str, int]]:
    """-> {plane_name: {event_name: total_ps}} (lines summed)."""
    out = {}
    for pname, per_line in aggregate_lines(path).items():
        agg = out.setdefault(pname, {})
        for line_agg in per_line:
            for name, ps in line_agg.items():
                agg[name] = agg.get(name, 0) + ps
    return out


_INSTR_LIKE = re.compile(r"[\w.\-]+\Z")


def instr_like(name: str) -> bool:
    """True when an event name looks like an HLO instruction ('dot.4',
    'fusion.12', 'reduce-window') rather than host bookkeeping. Host
    planes interleave python-source events ('$profiler.py:226 trace'),
    runtime markers ('TfrtCpuExecutable::Execute',
    'ThunkExecutor::Execute (wait...)') and dispatch wrappers
    ('PjitFunction(f)') with the real instruction events — all of which
    contain '$', ':', '(', or spaces that no instruction name can."""
    return _INSTR_LIKE.fullmatch(name) is not None


def aggregate_dir(trace_dir) -> Dict[str, int]:
    """Merge the DEVICE planes of every .xplane.pb under trace_dir into ONE
    {event_name: total_ps} map. Within a device plane an instruction shows
    up once per line that mentions it (raw XLA-op line + derived
    step/module lines), so per plane we take the per-name MAX across lines
    — one line's worth, not the double-counted sum — then sum across planes
    (per-core time adds up) and files.

    Fallback: traces with no '/device:' plane at all (e.g. CPU-backend jax
    writes only host planes) merge the host planes instead — with the SAME
    per-name max-across-lines dedup (host planes repeat events on derived
    lines too), and restricted to instruction-like event names so python
    source events and runtime markers (`instr_like`) don't swamp the
    table."""
    device: Dict[str, int] = {}
    host: Dict[str, int] = {}
    for p in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True):
        for pname, per_line in aggregate_lines(p).items():
            target = device if pname.startswith("/device:") else host
            plane: Dict[str, int] = {}
            for line_agg in per_line:
                for name, ps in line_agg.items():
                    if target is host and not instr_like(name):
                        continue
                    plane[name] = max(plane.get(name, 0), ps)
            for name, ps in plane.items():
                target[name] = target.get(name, 0) + ps
    return device if device else host


_HLO_LINE = re.compile(
    r"%?([\w.\-]+)\s*=\s*\S.*metadata=\{[^}]*op_name=\"([^\"]*)\"")
_PD_SCOPE = re.compile(r"pd\.([A-Za-z0-9_@]+)")


def hlo_op_names(hlo_text: str) -> Dict[str, str]:
    """{instruction_name: ir_op_type} from optimized-HLO text, using the
    pd.<type> named-scope component of each op_name (instructions outside
    any pd scope — infeed, copies, jax-internal reductions — map to their
    trailing op_name component)."""
    out: Dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = _HLO_LINE.search(line)
        if not m:
            continue
        instr, op_name = m.group(1), m.group(2)
        pd = _PD_SCOPE.search(op_name)
        if pd:
            out[instr] = pd.group(1)
        else:
            tail = [t for t in op_name.split("/") if t]
            out[instr] = tail[-1] if tail else op_name
    return out


def attribute(instr_ps: Dict[str, int],
              opname_by_instr: Dict[str, str],
              other_label: Optional[str] = None) -> Dict[str, int]:
    """Join per-instruction timings with the HLO mapping -> per-IR-op-type
    total picoseconds. Events with no HLO mapping (host bookkeeping,
    runtime internals) are dropped, or pooled under `other_label`."""
    agg: Dict[str, int] = {}
    for instr, ps in instr_ps.items():
        op = opname_by_instr.get(instr)
        if op is None:
            if other_label is None:
                continue
            op = other_label
        agg[op] = agg.get(op, 0) + ps
    return agg


def category(name: str) -> str:
    """HLO instruction text -> coarse op kind ('%fusion.123 = ...' ->
    'fusion'; falls back to the leading token)."""
    tok = name.lstrip("%").split(" ", 1)[0]
    return tok.split(".")[0]
