"""XSpace/XPlane (.xplane.pb) wire-format parser + HLO->IR-op attribution.

jax.profiler.trace writes xplane protos; the tensorboard profile plugin in
this image can't load them (TF version skew), so this decodes the wire
format directly — only the fields needed to aggregate device-op time:

  XSpace.planes=1 / XPlane{name=2, lines=3, event_metadata=4}
  XLine{name=2, timestamp_ns=3, events=4}
  XEvent{metadata_id=1, offset_ps=2, duration_ps=3}
  XEventMetadata map entry {key=1, value=2} / XEventMetadata{id=1, name=2}

The executor wraps every IR op's lowering in jax.named_scope("pd.<type>")
(executor._exec_op), so the compiled module's per-instruction
`metadata={op_name="jit(fn)/.../pd.<type>/<prim>"}` carries the IR op that
emitted each HLO instruction — including the representative op of each
fusion. `hlo_op_names` extracts that mapping from the optimized HLO text
and `attribute` joins it with the xplane per-instruction timings, giving
the reference ParseEvents-style "which op eats the step" table for the
whole-block jit (reference platform/profiler.h:137-166)."""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, Optional

__all__ = ["aggregate", "aggregate_dir", "aggregate_lines", "hlo_op_names",
           "attribute", "category", "fields", "parse_plane",
           "plane_events", "timeline_dir", "COLLECTIVE_KINDS",
           "collective_kind", "hlo_collectives", "exposed_in_line",
           "collective_events_dir"]


def _varint(buf, i):
    r = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, i
        shift += 7


def fields(buf):
    """Yield (field_number, wire_type, value) over a serialized message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i: i + ln]
            i += ln
        elif wt == 5:
            v = buf[i: i + 4]
            i += 4
        elif wt == 1:
            v = buf[i: i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


def parse_plane(buf):
    name = ""
    lines = []
    meta = {}
    for fno, wt, v in fields(buf):
        if fno == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            lines.append(v)
        elif fno == 4 and wt == 2:
            k = None
            mname = None
            for f2, w2, v2 in fields(v):
                if f2 == 1 and w2 == 0:
                    k = v2
                elif f2 == 2 and w2 == 2:
                    for f3, w3, v3 in fields(v2):
                        if f3 == 1 and w3 == 0 and k is None:
                            k = v3
                        elif f3 == 2 and w3 == 2:
                            mname = v3.decode("utf-8", "replace")
            if k is not None and mname is not None:
                meta[k] = mname
    return name, lines, meta


def aggregate_lines(path) -> Dict[str, list]:
    """-> {plane_name: [{event_name: total_ps} per XLine]} — per-line
    aggregation so callers can dedup a plane's derived lines (xplane device
    planes repeat each instruction on the raw XLA-op line AND on derived
    step/module/framework-op lines)."""
    buf = open(path, "rb").read()
    out: Dict[str, list] = {}
    for fno, wt, v in fields(buf):
        if fno != 1 or wt != 2:
            continue
        pname, lines, meta = parse_plane(v)
        per_line = out.setdefault(pname, [])
        for line in lines:
            agg: Dict[str, int] = {}
            for f2, w2, v2 in fields(line):
                if f2 != 4 or w2 != 2:   # XLine.events
                    continue
                mid = dur = 0
                for f3, w3, v3 in fields(v2):
                    if f3 == 1 and w3 == 0:
                        mid = v3
                    elif f3 == 3 and w3 == 0:
                        dur = v3
                name = meta.get(mid, f"#{mid}")
                agg[name] = agg.get(name, 0) + dur
            per_line.append(agg)
    return out


def plane_events(path) -> Dict[str, list]:
    """-> {plane_name: [line, ...]} where each line is
    {"name": str, "timestamp_ns": int,
     "events": [(event_name, offset_ps, duration_ps), ...]}.

    The full-resolution view of the same planes `aggregate_lines` sums:
    XLine.timestamp_ns anchors the line on the wall clock and
    XEvent.offset_ps places each event within the line, so
    timestamp_ns*1e3 + offset_ps orders events across lines and planes —
    the timeline the waterfall/duty-cycle analysis needs."""
    buf = open(path, "rb").read()
    out: Dict[str, list] = {}
    for fno, wt, v in fields(buf):
        if fno != 1 or wt != 2:
            continue
        pname, lines, meta = parse_plane(v)
        per_line = out.setdefault(pname, [])
        for line in lines:
            lname = ""
            ts_ns = 0
            events = []
            for f2, w2, v2 in fields(line):
                if f2 == 2 and w2 == 2:      # XLine.name
                    lname = v2.decode("utf-8", "replace")
                elif f2 == 3 and w2 == 0:    # XLine.timestamp_ns
                    ts_ns = v2
                elif f2 == 4 and w2 == 2:    # XLine.events
                    mid = off = dur = 0
                    for f3, w3, v3 in fields(v2):
                        if f3 == 1 and w3 == 0:
                            mid = v3
                        elif f3 == 2 and w3 == 0:
                            off = v3
                        elif f3 == 3 and w3 == 0:
                            dur = v3
                    events.append((meta.get(mid, f"#{mid}"), off, dur))
            per_line.append({"name": lname, "timestamp_ns": ts_ns,
                             "events": events})
    return out


def timeline_dir(trace_dir) -> list:
    """Merge every .xplane.pb under trace_dir into a flat list of
    {"plane", "line", "timestamp_ns", "events"} records (events carry
    (name, offset_ps, duration_ps)), device planes first."""
    records = []
    for p in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True):
        for pname, lines in plane_events(p).items():
            for line in lines:
                records.append({"plane": pname, "line": line["name"],
                                "timestamp_ns": line["timestamp_ns"],
                                "events": line["events"]})
    records.sort(key=lambda r: (not r["plane"].startswith("/device:"),
                                r["plane"], r["timestamp_ns"]))
    return records


def aggregate(path) -> Dict[str, Dict[str, int]]:
    """-> {plane_name: {event_name: total_ps}} (lines summed)."""
    out = {}
    for pname, per_line in aggregate_lines(path).items():
        agg = out.setdefault(pname, {})
        for line_agg in per_line:
            for name, ps in line_agg.items():
                agg[name] = agg.get(name, 0) + ps
    return out


_INSTR_LIKE = re.compile(r"[\w.\-]+\Z")


def instr_like(name: str) -> bool:
    """True when an event name looks like an HLO instruction ('dot.4',
    'fusion.12', 'reduce-window') rather than host bookkeeping. Host
    planes interleave python-source events ('$profiler.py:226 trace'),
    runtime markers ('TfrtCpuExecutable::Execute',
    'ThunkExecutor::Execute (wait...)') and dispatch wrappers
    ('PjitFunction(f)') with the real instruction events — all of which
    contain '$', ':', '(', or spaces that no instruction name can."""
    return _INSTR_LIKE.fullmatch(name) is not None


def aggregate_dir(trace_dir) -> Dict[str, int]:
    """Merge the DEVICE planes of every .xplane.pb under trace_dir into ONE
    {event_name: total_ps} map. Within a device plane an instruction shows
    up once per line that mentions it (raw XLA-op line + derived
    step/module lines), so per plane we take the per-name MAX across lines
    — one line's worth, not the double-counted sum — then sum across planes
    (per-core time adds up) and files.

    Fallback: traces with no '/device:' plane at all (e.g. CPU-backend jax
    writes only host planes) merge the host planes instead — with the SAME
    per-name max-across-lines dedup (host planes repeat events on derived
    lines too), and restricted to instruction-like event names so python
    source events and runtime markers (`instr_like`) don't swamp the
    table."""
    device: Dict[str, int] = {}
    host: Dict[str, int] = {}
    for p in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True):
        for pname, per_line in aggregate_lines(p).items():
            target = device if pname.startswith("/device:") else host
            plane: Dict[str, int] = {}
            for line_agg in per_line:
                for name, ps in line_agg.items():
                    if target is host and not instr_like(name):
                        continue
                    plane[name] = max(plane.get(name, 0), ps)
            for name, ps in plane.items():
                target[name] = target.get(name, 0) + ps
    return device if device else host


_HLO_LINE = re.compile(
    r"%?([\w.\-]+)\s*=\s*\S.*metadata=\{[^}]*op_name=\"([^\"]*)\"")
_PD_SCOPE = re.compile(r"pd\.([A-Za-z0-9_@]+)")
# framework collective call sites (jax.named_scope("pd.coll.<site>") in
# parallel/): the site component may contain dots, which _PD_SCOPE's
# character class deliberately excludes, so it gets its own regex
_PD_COLL = re.compile(r"pd\.coll\.([A-Za-z0-9_.\-]+)")


def hlo_op_names(hlo_text: str) -> Dict[str, str]:
    """{instruction_name: ir_op_type} from optimized-HLO text, using the
    pd.<type> named-scope component of each op_name (instructions outside
    any pd scope — infeed, copies, jax-internal reductions — map to their
    trailing op_name component). Instructions inside a pd.coll.<site>
    collective scope map to 'coll.<site>' so the roofline table shows the
    emitting call site, not a bare 'coll'."""
    out: Dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = _HLO_LINE.search(line)
        if not m:
            continue
        instr, op_name = m.group(1), m.group(2)
        coll = _PD_COLL.search(op_name)
        if coll:
            out[instr] = "coll." + coll.group(1)
            continue
        pd = _PD_SCOPE.search(op_name)
        if pd:
            out[instr] = pd.group(1)
        else:
            tail = [t for t in op_name.split("/") if t]
            out[instr] = tail[-1] if tail else op_name
    return out


def attribute(instr_ps: Dict[str, int],
              opname_by_instr: Dict[str, str],
              other_label: Optional[str] = None) -> Dict[str, int]:
    """Join per-instruction timings with the HLO mapping -> per-IR-op-type
    total picoseconds. Events with no HLO mapping (host bookkeeping,
    runtime internals) are dropped, or pooled under `other_label`."""
    agg: Dict[str, int] = {}
    for instr, ps in instr_ps.items():
        op = opname_by_instr.get(instr)
        if op is None:
            if other_label is None:
                continue
            op = other_label
        agg[op] = agg.get(op, 0) + ps
    return agg


def category(name: str) -> str:
    """HLO instruction text -> coarse op kind ('%fusion.123 = ...' ->
    'fusion'; falls back to the leading token)."""
    tok = name.lstrip("%").split(" ", 1)[0]
    return tok.split(".")[0]


# --- collective classification ----------------------------------------------

# (kind, substring patterns) in match order. Covers the HLO spellings
# ('all-reduce.3', 'all-gather-start'), the squashed forms some runtimes
# emit ('AllReduce'), and the framework-level names ('ppermute'). The
# first matching kind wins, so narrower kinds must precede kinds whose
# patterns are substrings of theirs (tools/check_registry.py lints this
# table for self-consistency: every pattern must classify as its own
# kind, or a new entry silently falls into another bucket).
COLLECTIVE_KINDS = (
    ("reduce-scatter", ("reduce-scatter", "reducescatter",
                        "reduce_scatter")),
    ("all-reduce", ("all-reduce", "allreduce", "all_reduce",
                    "cross-replica-sum")),
    ("all-gather", ("all-gather", "allgather", "all_gather")),
    ("all-to-all", ("all-to-all", "alltoall", "all_to_all")),
    ("collective-permute", ("collective-permute", "collectivepermute",
                            "collective_permute", "ppermute")),
    ("send/recv", ("send", "recv")),
)

# busbw factor per kind (nccl-tests convention): the ratio of bytes that
# actually cross links to bytes in the buffer, as a function of the
# participant count n. all-reduce moves each byte out and back
# (2(n-1)/n), gather/scatter/alltoall move the (n-1)/n remote fraction,
# a permute hop and a send/recv pair move the whole buffer once.
_BUSBW_FACTOR = {
    "all-reduce": lambda n: 2.0 * (n - 1) / n if n > 1 else 0.0,
    "all-gather": lambda n: (n - 1) / n if n > 1 else 0.0,
    "reduce-scatter": lambda n: (n - 1) / n if n > 1 else 0.0,
    "all-to-all": lambda n: (n - 1) / n if n > 1 else 0.0,
    "collective-permute": lambda n: 1.0,
    "send/recv": lambda n: 1.0,
}


def collective_kind(name: str) -> Optional[str]:
    """Collective kind for an HLO instruction / xplane event name, or None
    for non-collective events ('fusion.3', 'dot.1', 'infeed')."""
    low = name.lower()
    for kind, pats in COLLECTIVE_KINDS:
        if any(p in low for p in pats):
            return kind
    return None


def busbw_factor(kind: str, n: int) -> float:
    fn = _BUSBW_FACTOR.get(kind)
    return fn(max(int(n), 1)) if fn else 0.0


# dtype token -> bytes per element for HLO shape strings ('f32[4,128]')
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_TOK = re.compile(r"([a-z]+[0-9]*[a-z0-9]*)\[([0-9,]*)\]")


def _shape_bytes(shape_text: str) -> int:
    """Payload bytes of an HLO shape string — 'f32[4,128]{1,0}', 'bf16[]'
    or a tuple '(f32[8], f32[32])'. Async '-start' ops carry (input,
    output) tuples aliasing one transfer, so tuples report their largest
    component, not the sum. Unknown dtypes count 4 bytes/elem."""
    sizes = []
    for dtype, dims in _SHAPE_TOK.findall(shape_text):
        if dtype == "token":
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _DTYPE_BYTES.get(dtype, 4))
    if not sizes:
        return 0
    if shape_text.lstrip().startswith("("):
        return max(sizes)
    return sum(sizes)


_HLO_COLL = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\([^)]*\)|[\w\[\]{},:]+)\s+([\w\-]+)(?:\(|\b)")


def hlo_collectives(hlo_text: str) -> Dict[str, dict]:
    """{instruction_name: {"kind", "site", "bytes"}} for the collective
    instructions of one optimized-HLO module. kind is classified from the
    opcode via COLLECTIVE_KINDS; site is the pd.coll.<site> named-scope
    component of metadata op_name (None for GSPMD-inserted collectives
    outside any tagged region); bytes is the output-shape payload — the
    '-done' half of an async start/done pair reports 0 bytes so the pair's
    payload is not double-counted (its device time still joins the site)."""
    out: Dict[str, dict] = {}
    for line in hlo_text.splitlines():
        m = _HLO_COLL.match(line)
        if not m:
            continue
        instr, shape, opcode = m.group(1), m.group(2), m.group(3)
        kind = collective_kind(opcode)
        if kind is None:
            continue
        site = near = None
        mm = _HLO_LINE.search(line)
        if mm:
            c = _PD_COLL.search(mm.group(2))
            if c:
                site = c.group(1)
            else:
                # GSPMD-inserted collective: no framework line emitted it,
                # but it inherits the op_name of the op it was split from —
                # the pd.<op_type> scope names the responsible layer
                s = _PD_SCOPE.search(mm.group(2))
                if s:
                    near = s.group(1)
        nbytes = 0 if opcode.endswith("-done") else _shape_bytes(shape)
        out[instr] = {"kind": kind, "site": site, "near": near,
                      "bytes": nbytes}
    return out


def hlo_participants(hlo_text: str) -> Optional[int]:
    """Participant count of the module's collectives, parsed from
    replica_groups — either the iota form '<=[4]' or explicit groups
    '{{0,1,2,3}}'. None when the module has no replica_groups."""
    m = re.search(r"replica_groups=\[[0-9,]+\]<=\[(\d+)\]", hlo_text)
    if m:
        return int(m.group(1))
    m = re.search(r"replica_groups=\{\{([0-9, ]+)\}", hlo_text)
    if m:
        return len([t for t in m.group(1).split(",") if t.strip()])
    return None


def exposed_in_line(events) -> Dict[str, int]:
    """{collective_event_name: exposed_ps} for one line's (name, offset_ps,
    duration_ps) events: the part of each collective's duration covered by
    NO concurrent non-collective event. An async all-reduce whose '-done'
    wait runs under a fusion kernel is hidden (overlapped); collective
    time with nothing else on the line is exposed step time."""
    other = []
    colls = []
    for name, off, dur in events:
        if dur <= 0:
            continue
        if collective_kind(name) is None:
            other.append((off, off + dur))
        else:
            colls.append((name, off, off + dur))
    # merge the non-collective intervals once
    other.sort()
    merged = []
    for s, e in other:
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    out: Dict[str, int] = {}
    for name, s, e in colls:
        covered = 0
        for ms, me in merged:
            if me <= s:
                continue
            if ms >= e:
                break
            covered += min(e, me) - max(s, ms)
        out[name] = out.get(name, 0) + max((e - s) - covered, 0)
    return out


def collective_events_dir(trace_dir) -> Dict[str, dict]:
    """Merge every .xplane.pb under trace_dir into {event_name: {"kind",
    "total_ps", "exposed_ps"}} for the collective events. Same dedup
    discipline as aggregate_dir — per plane take each name's MAX across
    lines (derived step/module lines repeat the raw XLA-op line; on CPU
    traces collective work also lands on per-device thread lines, so a
    busiest-line-only pick would miss it), then sum across planes and
    files. exposed_ps comes from the line that contributed the max: the
    part of the collective's duration no concurrent non-collective event
    on that line covers."""
    device_planes = []
    host_planes = []
    for p in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True):
        for pname, lines in plane_events(p).items():
            if pname.startswith("/device:"):
                device_planes.append(lines)
            else:
                filtered = []
                for line in lines:
                    evs = [e for e in line["events"] if instr_like(e[0])]
                    if evs:
                        filtered.append({**line, "events": evs})
                if filtered:
                    host_planes.append(filtered)
    planes = device_planes or host_planes
    out: Dict[str, dict] = {}
    for lines in planes:
        plane_best: Dict[str, tuple] = {}   # name -> (total_ps, exposed_ps)
        for line in lines:
            tot: Dict[str, int] = {}
            for name, _, dur in line["events"]:
                if collective_kind(name) is not None:
                    tot[name] = tot.get(name, 0) + dur
            if not tot:
                continue
            exposed = exposed_in_line(line["events"])
            for name, ps in tot.items():
                cur = plane_best.get(name)
                if cur is None or ps > cur[0]:
                    plane_best[name] = (ps, exposed.get(name, 0))
        for name, (ps, exp) in plane_best.items():
            rec = out.setdefault(name, {"kind": collective_kind(name),
                                        "total_ps": 0, "exposed_ps": 0})
            rec["total_ps"] += ps
            rec["exposed_ps"] += exp
    return out
