"""AOT inference serving engine (reference: the fluid inference library —
paddle/fluid/inference/io.cc pruned-ProgramDesc loading + the capi
GradientMachine serving surface; here re-imagined TPU-natively).

`ServingEngine` owns one pruned inference program end to end:

  * **Admission**: the program is pruned to the inference fetch set
    (`Program.prune` drops the training tail, including in-place optimizer
    updates), cloned `for_test`, and gated through the static analyzer —
    an error-severity diagnostic or a leaked training-only op refuses to
    serve rather than compile a broken artifact.
  * **AOT program cache**: one XLA executable per padded batch-size bucket
    (powers-of-two ladder by default), produced by `jit(fn).lower(avals)
    .compile()` — the same AOT pattern the executor's static memory
    analysis uses — and LRU-evicted under `cache_capacity`. Compiles are
    booked in `serving_compile_seconds`; lookups in
    `serving_cache_{hit,miss}_total{bucket=}`.
  * **Resident state**: persistable weights are device-put once at engine
    construction and thereafter round-trip through the executable's
    donated state argument (donation only off-CPU, matching
    Executor._jit_compile's contract) — serving never re-uploads weights.
    On a meshed program (fsdp-sharded DLRM tables) the first call shards
    host state per the program's in_shardings and the sharded device
    arrays become the residents.

Requests with LoD inputs (sequence models through the C-API) fall back to
the classic Executor.run path on the same pruned program — counted in
`serving_fallback_total{reason=}`, never silently.

`ServingEngine(..., quantize="int8")` serves the quantized program
(quant.py): weights are pre-quantized once at admission and baked into
the bucket executables as constants; activations get dynamic per-call
scales in-trace. Ineligible ops/weights fall back per
`quant_fallback_total{op,reason}` and serve at full precision.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry
from ..errors import ProgramVerifyError

DEFAULT_MAX_BATCH = 64

#: Op roles that must never appear in a served program. Op types are
#: checked structurally too (tools/check_registry.check_serving): a
#: `*_grad` suffix or an optimizer bucket type is training-only even when
#: an op_role attribute was lost along the way.
TRAINING_ONLY_ROLES = ("backward", "optimize")


def training_only_op_types() -> frozenset:
    """Op types that only make sense while training: every optimizer the
    fusion pass knows how to bucket, their fused/sparse twins, and the
    grad-accumulation helpers. Gradient ops are matched by their `_grad`
    suffix via `is_training_only_op` instead of enumeration."""
    from ..ops import fusion
    out = set(fusion.OPTIMIZER_BUCKET_OPS)
    out.update(t for t in fusion.FUSED_OP_TYPES
               if "sparse" in t or any(o in t for o in
                                       fusion.OPTIMIZER_BUCKET_OPS))
    return frozenset(out)


def is_training_only_op(op_type: str, op_role: Optional[str]) -> bool:
    return (op_role in TRAINING_ONLY_ROLES
            or op_type.endswith("_grad")
            or op_type in training_only_op_types())


def bucket_ladder(max_batch: int = DEFAULT_MAX_BATCH) -> Tuple[int, ...]:
    """Powers-of-two padded batch sizes up to and including max_batch."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


def _pad_rows(arr: np.ndarray, rows: int) -> np.ndarray:
    """Pad axis 0 to `rows` by repeating the last row: edge padding keeps
    values in-distribution (no NaN from log(0)-style ops on zero rows) and
    the mask is implicit — only the first n result rows are returned."""
    n = arr.shape[0]
    if n == rows:
        return arr
    pad = np.repeat(arr[-1:], rows - n, axis=0)
    return np.concatenate([arr, pad], axis=0)


class ServingEngine:
    """AOT-compiled serving for one inference program.

    `model` is either a `save_inference_model` directory (loaded into a
    private scope) or an in-memory Program (pruned here; weights read from
    `scope`/the global scope). `infer(feed)` is the synchronous
    single-caller surface; `run_batch` is the batcher's hot path.
    """

    def __init__(self, model, feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None, place=None,
                 scope=None, max_batch: int = DEFAULT_MAX_BATCH,
                 buckets: Optional[Sequence[int]] = None,
                 cache_capacity: Optional[int] = None,
                 emb_cache_budget_bytes: Optional[int] = None,
                 emb_cache_tables: Optional[Dict[str, int]] = None,
                 quantize: Optional[str] = None):
        from .. import io as io_mod
        from ..executor import (Executor, Scope, TPUPlace, scope_guard,
                                global_scope)

        self.place = place if place is not None else TPUPlace(0)
        self._exe = Executor(self.place)
        self.device = self._exe.device
        self._lock = threading.RLock()
        self._closed = False

        if isinstance(model, str):
            self._scope = Scope()
            with scope_guard(self._scope):
                program, loaded_feeds, fetch_targets = \
                    io_mod.load_inference_model(model, self._exe)
            feed_names = list(feed_names or loaded_feeds)
            fetch_names = list(fetch_names
                               or [v.name for v in fetch_targets])
        else:
            program = model
            if not feed_names or not fetch_names:
                raise ValueError(
                    "ServingEngine(program) needs explicit feed_names and "
                    "fetch_names (a model_dir carries them in __model__)")
            feed_names = list(feed_names)
            fetch_names = list(fetch_names)
            program = io_mod._strip_training_ops(program) \
                .prune(feed_names, fetch_names).clone(for_test=True)
            self._scope = scope if scope is not None else global_scope()

        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.program = program
        self._label = telemetry.program_label(program)

        # Quantized serving (quant.py): mark the pruned program O3 so the
        # serving trace routes eligible matmul/conv compute through int8
        # (or fp8), then pre-quantize persistable weights ONCE here,
        # host-side — the (q, scale) pairs bake into every bucket
        # executable as constants, so per-call cost is only the dynamic
        # activation scales inside the traced program. Ineligible weights
        # are counted in quant_fallback_total and served unquantized.
        self.quantize = quantize
        self.quant_report: Optional[Dict[str, object]] = None
        if quantize is not None:
            from .. import quant as quant_mod
            if quantize not in ("int8", "fp8"):
                raise ValueError(
                    f"quantize must be 'int8' or 'fp8', got {quantize!r}")
            program._amp_dtype = "bfloat16"
            program._amp_level = "O3"
            program._quant_mode = quantize
            self.quant_report = quant_mod.prequantize(
                program, self._scope, quantize)
            telemetry.log_event(
                "serving_prequantize", program=self._label, mode=quantize,
                quantized=len(self.quant_report["quantized"]),
                skipped=len(self.quant_report["skipped"]))

        self._admit(program, feed_names, fetch_names)

        # ladder + cache geometry
        if buckets is not None:
            self.buckets = tuple(sorted(set(int(b) for b in buckets)))
            if not self.buckets or self.buckets[0] < 1:
                raise ValueError(f"bad bucket ladder {buckets}")
        else:
            self.buckets = bucket_ladder(max_batch)
        self.max_batch = self.buckets[-1]
        self.cache_capacity = (int(cache_capacity) if cache_capacity
                               else len(self.buckets))

        # feed geometry from the program desc: leading dim must be the
        # batch (-1) for the bucket ladder to apply
        block = program.global_block()
        self._feed_meta: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {}
        for n in feed_names:
            v = block.desc.var(n)
            shape = tuple(int(d) for d in v.shape)
            if not shape or shape[0] != -1:
                raise ValueError(
                    f"feed '{n}' has static shape {shape}; serving buckets "
                    f"pad the leading batch dim, which must be -1")
            self._feed_meta[n] = (shape, np.dtype(str(v.dtype)))

        # compile the shared step fn once; per-bucket AOT executables are
        # lowered from it on demand
        self._compiled, self._state_names, self._persist_out = \
            self._exe.prepare_serving(program, feed_names, fetch_names,
                                      self._scope)

        # device-resident weights: committed to the serving device when
        # unmeshed; on a meshed program the first call distributes host
        # arrays per in_shardings and the sharded results become resident
        import jax
        self._state: Dict[str, object] = {}
        mesh = getattr(program, "_mesh", None)
        for n in self._state_names:
            v = self._scope.find_var(n)
            arr = np.asarray(v.array() if hasattr(v, "array") else v)
            self._state[n] = arr if mesh is not None \
                else jax.device_put(arr, self.device)

        # beyond-HBM tables (read-only hot-row cache, ISSUE 14): swap the
        # resident full table for a [cache_rows, dim] slab backed by a
        # host-DRAM authoritative copy; per-request ids remap to cache
        # slots under the engine lock in run_batch. Inference never
        # writes rows, so eviction never flushes. Must run before the
        # first bucket executable is lowered — the state avals change.
        self._emb_cache = None
        if emb_cache_budget_bytes is not None or emb_cache_tables:
            from ..parallel import emb_cache as emb_cache_mod
            self._emb_cache = emb_cache_mod.enable_serving(
                self, budget_bytes=emb_cache_budget_bytes,
                tables=emb_cache_tables)

        self._executables: "collections.OrderedDict[int, object]" = \
            collections.OrderedDict()
        # python-side mirrors of the telemetry counters (tests + stats())
        self.cache_hits = 0
        self.cache_misses = 0
        self.evictions = 0
        self.bucket_runs: Dict[int, int] = {}

    # --- admission ----------------------------------------------------------
    def _admit(self, program, feed_names, fetch_names):
        """PR 12 analyzer as an admission gate + training-op leak check."""
        leaked = [
            f"op[{i}] {op.type} (role={op.desc.attrs.get('op_role')})"
            for i, op in enumerate(program.global_block().ops)
            if is_training_only_op(op.type,
                                   op.desc.attrs.get("op_role"))]
        if leaked:
            raise ValueError(
                f"refusing to serve: training-only ops survived pruning: "
                f"{leaked} — the inference fetch set likely includes a "
                f"gradient or optimizer output")
        # a gradient fetch doesn't leak ops — its producer was stripped,
        # leaving the fetch uncomputable; refuse at admission instead of
        # failing obscurely at the first bucket compile
        block = program.global_block()
        produced = {n for op in block.ops for n in op.output_arg_names}
        for n in fetch_names:
            v = block.desc.vars.get(n)
            if (n not in produced and n not in feed_names
                    and not (v is not None and v.persistable)):
                raise ValueError(
                    f"refusing to serve: fetch '{n}' is not computable "
                    f"from the feeds — no op in the pruned program "
                    f"produces it (a gradient/optimizer output is not an "
                    f"inference fetch)")
        from ..analysis import analyze_program
        report = analyze_program(program, feeds=list(feed_names),
                                 fetches=list(fetch_names))
        if report.errors:
            raise ProgramVerifyError(report.errors,
                                     program_name="serving admission")
        telemetry.log_event("serving_admit", program=self._label,
                            ops=len(program.global_block().ops),
                            warnings=len(report.warnings))

    # --- bucket cache -------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _executable_locked(self, bucket: int):
        """AOT executable for one bucket — LRU cache with telemetry.
        Caller holds self._lock (the `_locked` suffix is the repo's
        convention for that contract; the thread lint enforces it)."""
        import jax
        from ..executor import _aval_of

        ex = self._executables.get(bucket)
        if ex is not None:
            self._executables.move_to_end(bucket)
            self.cache_hits += 1
            telemetry.counter(
                "serving_cache_hit_total",
                "serving bucket-executable cache hits",
                labels=("program", "bucket")).labels(
                    program=self._label, bucket=str(bucket)).inc()
            return ex
        self.cache_misses += 1
        telemetry.counter(
            "serving_cache_miss_total",
            "serving bucket-executable cache misses (AOT compiles)",
            labels=("program", "bucket")).labels(
                program=self._label, bucket=str(bucket)).inc()
        feed_avals = {
            n: jax.ShapeDtypeStruct((bucket,) + shape[1:], dtype)
            for n, (shape, dtype) in self._feed_meta.items()}
        state_avals = {n: _aval_of(v) for n, v in self._state.items()}
        t0 = time.perf_counter()
        ex = self._compiled.fn.lower(
            feed_avals, state_avals, np.uint32(0)).compile()
        dt = time.perf_counter() - t0
        telemetry.histogram(
            "serving_compile_seconds",
            "AOT lower+compile wall seconds per bucket executable",
            labels=("program", "bucket")).labels(
                program=self._label, bucket=str(bucket)).observe(dt)
        telemetry.log_event("serving_compile", program=self._label,
                            bucket=bucket, seconds=dt)
        self._executables[bucket] = ex
        while len(self._executables) > self.cache_capacity:
            evicted, _ = self._executables.popitem(last=False)
            self.evictions += 1
            telemetry.counter(
                "serving_cache_evictions_total",
                "bucket executables LRU-evicted",
                labels=("program",)).labels(program=self._label).inc()
            telemetry.log_event("serving_evict", program=self._label,
                                bucket=evicted)
        return ex

    # --- execution ----------------------------------------------------------
    def run_batch(self, feed: Dict[str, np.ndarray],
                  valid_rows: Optional[int] = None,
                  _phase_marks: Optional[Dict] = None) -> List[np.ndarray]:
        """Execute one coalesced batch: pad to the smallest admissible
        bucket, run its AOT executable, slice the valid rows back out.
        The donated state round-trips: the returned new_state (same
        buffers off-CPU) becomes the resident state for the next call.

        `_phase_marks`, when a dict, is filled with contiguous
        (start, end) monotonic pairs for the pad / bucket_select /
        compute phases (+ the chosen bucket) — the tracing hook the
        batcher uses to record per-request child spans retroactively."""
        if self.closed:
            raise RuntimeError("ServingEngine is closed")
        t_enter = time.monotonic() if _phase_marks is not None else 0.0
        arrays = {}
        n = None
        for name in self.feed_names:
            if name not in feed:
                raise KeyError(f"missing feed '{name}'; engine feeds: "
                               f"{self.feed_names}")
            shape, dtype = self._feed_meta[name]
            a = np.ascontiguousarray(feed[name], dtype=dtype)
            if a.ndim != len(shape):
                raise ValueError(
                    f"feed '{name}' rank {a.ndim} != declared {len(shape)}")
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    f"feeds disagree on batch: '{name}' has {a.shape[0]} "
                    f"rows, expected {n}")
            arrays[name] = a
        if n == 0:
            raise ValueError("empty batch")
        if n > self.max_batch:
            raise ValueError(
                f"batch {n} exceeds the largest bucket {self.max_batch}; "
                f"split the request (infer() chunks automatically)")
        rows = valid_rows if valid_rows is not None else n
        bucket = self.bucket_for(n)
        with self._lock:
            if self._emb_cache is not None:
                # ids -> cache slots (misses stage from the host slab
                # into self._state's slab); padding afterwards repeats
                # the last row, so pad rows carry valid slot ids
                arrays = self._emb_cache.prepare_feed(arrays)
            padded = {name: _pad_rows(a, bucket)
                      for name, a in arrays.items()}
            if _phase_marks is not None:
                t_pad = time.monotonic()
                _phase_marks["bucket"] = bucket
                _phase_marks["pad"] = (t_enter, t_pad)
            ex = self._executable_locked(bucket)
            if _phase_marks is not None:
                t_sel = time.monotonic()
                _phase_marks["bucket_select"] = (t_pad, t_sel)
            fetch, _lens, new_state = ex(padded, self._state,
                                         np.uint32(0))
            if _phase_marks is not None:
                _phase_marks["compute"] = (t_sel, time.monotonic())
            self._state = new_state
            self.bucket_runs[bucket] = \
                self.bucket_runs.get(bucket, 0) + 1
        telemetry.counter(
            "serving_bucket_runs_total",
            "batches executed per bucket",
            labels=("program", "bucket")).labels(
                program=self._label, bucket=str(bucket)).inc()
        return [np.asarray(f)[:rows] for f in fetch]

    def infer(self, feed: Dict[str, object]) -> List[np.ndarray]:
        """Synchronous single-caller inference. Dense feeds go through the
        bucketed AOT path (chunked when larger than the top bucket);
        LoDTensor feeds fall back to the classic executor on the same
        pruned program."""
        from ..executor import LoDTensor, scope_guard

        if self.closed:
            raise RuntimeError("ServingEngine is closed")
        if any(isinstance(feed.get(n), LoDTensor) and feed[n].lod
               for n in self.feed_names):
            telemetry.counter(
                "serving_fallback_total",
                "requests served by the non-AOT executor path",
                labels=("program", "reason")).labels(
                    program=self._label, reason="lod").inc()
            with self._lock:
                with scope_guard(self._scope):
                    outs = self._exe.run(self.program, feed=dict(feed),
                                         fetch_list=list(self.fetch_names),
                                         scope=self._scope)
            return [np.asarray(o) for o in outs]

        arrays = {n: np.asarray(feed[n]) for n in self.feed_names}
        n = arrays[self.feed_names[0]].shape[0]
        if n <= self.max_batch:
            return self.run_batch(arrays)
        parts = []
        for start in range(0, n, self.max_batch):
            chunk = {k: v[start:start + self.max_batch]
                     for k, v in arrays.items()}
            parts.append(self.run_batch(chunk))
        return [np.concatenate([p[i] for p in parts], axis=0)
                for i in range(len(self.fetch_names))]

    # --- lifecycle / introspection ------------------------------------------
    def stats(self) -> Dict[str, object]:
        # under the run lock: counters and resident state are mutated by
        # the batcher worker mid-run_batch, and stats() is called from
        # client/monitoring threads
        with self._lock:
            out = {
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "evictions": self.evictions,
                "bucket_runs": dict(self.bucket_runs),
                "buckets": list(self.buckets),
                "resident_state": len(self._state or ()),
            }
        if self._emb_cache is not None:
            out["emb_cache"] = self._emb_cache.stats()
        return out

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self):
        """Destroy-handle semantics (C-API `paddle_tpu_machine_destroy`):
        drop executables and resident device state; further calls raise."""
        with self._lock:
            self._executables.clear()
            self._state = {}
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
