"""Per-model SLOs with multi-window burn-rate evaluation.

An `SLO` declares what "good" means for one served model: an
availability objective (fraction of submitted requests answered — sheds,
deadline expiries, and engine failures all spend the error budget) and
an optional latency objective (a request slower than `latency_ms` counts
as bad even though it completed). `SLOMonitor` consumes one outcome per
request from the batcher and evaluates **burn rate** over two sliding
windows, the standard multi-window alerting shape:

    burn = (bad / total in window) / (1 - availability)

A burn rate of 1.0 means the error budget is being spent exactly at the
sustainable rate; the fast window (5 min) catches an active incident in
minutes, the slow window (1 h) confirms it is not a blip. Both surface
as `slo_burn_rate{model,window}` gauges, in `batcher.stats()["slo"]`,
in `overload_report`'s `slo` sub-dict, and in `/healthz` (obs_server),
which reports "degraded" when any model's fast window burns > 1.

Monitors register in a process-wide table (`monitor_for`) so the obs
endpoint can report on every served model without holding batcher refs.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Optional

from .. import telemetry

FAST_WINDOW_S = 300.0     # 5 min: page-fast incident detection
SLOW_WINDOW_S = 3600.0    # 1 h: sustained-burn confirmation

_REGISTRY_LOCK = threading.Lock()
_MONITORS: "Dict[str, SLOMonitor]" = {}


class SLO:
    """Objectives for one model. `availability` is the target fraction of
    good requests (error budget = 1 - availability); `latency_ms`, when
    set, marks slower-than-objective successes as bad too."""

    __slots__ = ("model", "availability", "latency_ms")

    def __init__(self, model: str, availability: float = 0.999,
                 latency_ms: Optional[float] = None):
        if not 0.0 < availability < 1.0:
            raise ValueError(
                f"availability must be in (0, 1), got {availability}")
        self.model = model
        self.availability = float(availability)
        self.latency_ms = float(latency_ms) if latency_ms is not None \
            else None

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability

    def to_dict(self) -> Dict[str, object]:
        return {"model": self.model, "availability": self.availability,
                "latency_ms": self.latency_ms,
                "error_budget": self.error_budget}


class SLOMonitor:
    """Sliding-window burn-rate evaluator for one SLO.

    `record()` is O(1) append under a lock (called from the batcher's
    worker and submit paths); `burn_rate()`/`report()` prune expired
    samples lazily. `clock` is injectable for deterministic tests."""

    def __init__(self, slo: SLO, max_samples: int = 65536,
                 clock: Callable[[], float] = time.monotonic):
        self.slo = slo
        self.clock = clock
        self._lock = threading.Lock()
        # (t, bad) pairs; bounded so a scrape-less process can't grow it
        self._samples: "collections.deque" = collections.deque(
            maxlen=int(max_samples))

    def record(self, ok: bool, latency_s: Optional[float] = None):
        """One request outcome. `ok=False` for sheds/failures; a
        completed request is still bad when it misses the latency
        objective."""
        bad = not ok
        if (not bad and latency_s is not None
                and self.slo.latency_ms is not None
                and latency_s * 1e3 > self.slo.latency_ms):
            bad = True
        with self._lock:
            self._samples.append((self.clock(), bad))

    def _window_counts(self, window_s: float, now: float):
        cutoff = now - window_s
        # only ever prune to the slow window — a fast-window query must
        # not destroy history the slow window still needs
        keep_cutoff = now - SLOW_WINDOW_S
        with self._lock:
            while self._samples and self._samples[0][0] < keep_cutoff:
                self._samples.popleft()
            # deque is time-ordered; after pruning to the slow window,
            # count the sub-window by scanning from the newest end
            total = bad = 0
            for t, b in reversed(self._samples):
                if t < cutoff:
                    break
                total += 1
                bad += b
        return total, bad

    def burn_rate(self, window_s: float = FAST_WINDOW_S,
                  now: Optional[float] = None) -> float:
        """Error-budget burn over the window; 0.0 when no traffic."""
        now = self.clock() if now is None else now
        total, bad = self._window_counts(window_s, now)
        if total == 0:
            return 0.0
        return (bad / total) / self.slo.error_budget

    def report(self, now: Optional[float] = None) -> Dict[str, object]:
        """Burn rates for both windows + raw counts; refreshes the
        `slo_burn_rate{model,window}` gauges as a side effect so scrapes
        see what the report saw."""
        now = self.clock() if now is None else now
        gauge = telemetry.gauge(
            "slo_burn_rate",
            "error-budget burn rate (1.0 = sustainable spend), by window",
            labels=("model", "window"))
        windows = {}
        for wname, wsec in (("fast", FAST_WINDOW_S), ("slow",
                                                      SLOW_WINDOW_S)):
            total, bad = self._window_counts(wsec, now)
            burn = ((bad / total) / self.slo.error_budget if total
                    else 0.0)
            gauge.labels(model=self.slo.model, window=wname).set(burn)
            windows[wname] = {"window_s": wsec, "total": total,
                              "bad": bad,
                              "error_rate": bad / total if total else 0.0,
                              "burn_rate": burn}
        return {"objective": self.slo.to_dict(), "windows": windows}


def monitor_for(model: str, slo: Optional[SLO] = None,
                **slo_kwargs) -> SLOMonitor:
    """Get-or-create the process-wide monitor for `model`. The first
    caller's objectives stick; later callers get the same monitor."""
    with _REGISTRY_LOCK:
        mon = _MONITORS.get(model)
        if mon is None:
            mon = SLOMonitor(slo or SLO(model, **slo_kwargs))
            _MONITORS[model] = mon
        return mon


def all_reports(now: Optional[float] = None) -> Dict[str, Dict]:
    """`report()` for every registered model (the /healthz + /report
    view)."""
    with _REGISTRY_LOCK:
        mons = dict(_MONITORS)
    return {model: mon.report(now=now) for model, mon in mons.items()}


def reset():
    """Drop all registered monitors (tests)."""
    with _REGISTRY_LOCK:
        _MONITORS.clear()
