"""Dynamic request batching + overload control for the serving engine.

`DynamicBatcher` is the concurrency layer between many client threads and
one `ServingEngine`: clients `submit(feed)` and get a
`concurrent.futures.Future`; a single worker thread coalesces queued
requests into the largest batch that fits the engine's top bucket, closes
the batch on **size-full OR deadline** (whichever first — small batches
don't wait forever, hot queues don't fragment), executes it through the
engine's AOT bucket cache, and scatters per-request result slices back to
the futures.

Overload control is reject-not-collapse: the queue is bounded
(`max_queue_depth` requests) and a full queue sheds new submissions with
`ServingOverloadError(reason="queue_full")` instead of letting latency
grow without bound; requests whose per-request deadline expires while
still queued are shed at batch-close with `reason="deadline"` rather than
wasting device time on answers nobody is waiting for. Goodput under
overload — the fraction of submitted requests that complete in time —
is the metric this policy optimizes, and `stats()`/telemetry expose it:
`serving_queue_depth` (gauge), `serving_shed_total{reason}`,
`serving_batches_total{close}`, and `serving_request_seconds{phase}`
histograms with phase in queue/compute/total (p50/p99 via
telemetry.histogram_quantile).
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from .. import telemetry
from .. import tracing
from ..errors import ServingOverloadError
from . import slo as slo_mod


class _Request:
    __slots__ = ("feed", "rows", "future", "submit_t", "deadline_t",
                 "span")

    def __init__(self, feed, rows, deadline_t):
        self.feed = feed
        self.rows = rows
        self.future: Future = Future()
        self.submit_t = time.monotonic()
        self.deadline_t = deadline_t
        self.span = None


class DynamicBatcher:
    """Coalesce concurrent variable-size requests into bucketed batches.

    Not started by construction: call `start()` (or use as a context
    manager). A constructed-but-unstarted batcher accepts submissions into
    the bounded queue without draining it — deterministic ground for
    queue-full shedding tests.
    """

    def __init__(self, engine, max_batch: Optional[int] = None,
                 max_delay_ms: float = 5.0, max_queue_depth: int = 64,
                 slo: Optional["slo_mod.SLO"] = None):
        self.engine = engine
        self.max_batch = int(max_batch or engine.max_batch)
        if self.max_batch > engine.max_batch:
            raise ValueError(
                f"batcher max_batch {self.max_batch} exceeds the engine's "
                f"top bucket {engine.max_batch}")
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue_depth = int(max_queue_depth)
        self._label = getattr(engine, "_label", "p?")
        # every request outcome (completed / shed / failed) feeds the
        # model's burn-rate monitor; shared process-wide by model label
        # so /healthz sees it too
        self.slo_monitor = slo_mod.monitor_for(self._label, slo=slo)
        self._cond = threading.Condition()
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._pending_rows = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # python-side mirrors of the telemetry series (tests + stats())
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.close_counts: Dict[str, int] = {}

    # --- client side --------------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the list of
        fetch arrays (this request's rows only). Sheds immediately —
        ServingOverloadError raised here, not via the future — when the
        queue is full or the batcher is stopped."""
        rows = None
        for name in self.engine.feed_names:
            if name not in feed:
                raise KeyError(f"missing feed '{name}'; engine feeds: "
                               f"{self.engine.feed_names}")
            n = np.asarray(feed[name]).shape[0]
            rows = n if rows is None else rows
            if n != rows:
                raise ValueError(f"feeds disagree on rows: '{name}' has "
                                 f"{n}, expected {rows}")
        if rows == 0:
            raise ValueError("empty request")
        if rows > self.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch "
                f"{self.max_batch}; split it client-side or call "
                f"engine.infer() directly")
        deadline_t = (time.monotonic() + deadline_ms / 1000.0
                      if deadline_ms is not None else None)
        with self._cond:
            self.submitted += 1
            if self._stop:
                self._shed_locked("shutdown")
                raise ServingOverloadError(
                    "serving batcher is stopped", reason="shutdown",
                    queue_depth=len(self._queue))
            if len(self._queue) >= self.max_queue_depth:
                self._shed_locked("queue_full")
                raise ServingOverloadError(
                    f"serving queue full ({len(self._queue)} requests "
                    f">= max_queue_depth {self.max_queue_depth})",
                    reason="queue_full", queue_depth=len(self._queue))
            req = _Request(feed, rows, deadline_t)
            if tracing.enabled():
                req.span = tracing.start_span(
                    "serving_request", parent=None,
                    attrs={"program": self._label, "rows": rows})
                # pin the span start to the submit timestamp so the
                # queue child tiles the parent exactly
                if req.span.sampled:
                    req.span.start = req.submit_t
            self._queue.append(req)
            self._pending_rows += rows
            self._depth_gauge_locked()
            self._cond.notify_all()
        return req.future

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> "DynamicBatcher":
        with self._cond:
            if self._thread is not None:
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._worker, name="serving-batcher", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True):
        """Stop the worker. With drain=True (default) queued requests are
        still executed; with drain=False they are shed with
        reason="shutdown"."""
        with self._cond:
            self._stop = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._pending_rows -= req.rows
                    self._shed_locked("shutdown")
                    if req.span is not None:
                        req.span.end(outcome="shed", reason="shutdown")
                    req.future.set_exception(ServingOverloadError(
                        "serving batcher shut down", reason="shutdown",
                        queue_depth=len(self._queue)))
                self._depth_gauge_locked()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # --- worker -------------------------------------------------------------
    def _worker(self):
        while True:
            batch, close = self._collect()
            if batch is None:
                return
            if batch:
                self._execute(batch, close)

    def _collect(self):
        """Block until a batch is ready; return (requests, close_reason).
        (None, _) signals worker exit. The batch window opens at the first
        queued request and closes when pending rows reach max_batch
        ("size") or max_delay elapses ("deadline")."""
        with self._cond:
            while not self._queue:
                if self._stop:
                    return None, ""
                self._cond.wait(0.05)
            close_t = self._queue[0].submit_t + self.max_delay
            while self._pending_rows < self.max_batch and not self._stop:
                remaining = close_t - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            close = ("size" if self._pending_rows >= self.max_batch
                     else "deadline")
            batch: List[_Request] = []
            rows = 0
            while self._queue and rows + self._queue[0].rows \
                    <= self.max_batch:
                req = self._queue.popleft()
                self._pending_rows -= req.rows
                batch.append(req)
                rows += req.rows
            self._depth_gauge_locked()
            self.close_counts[close] = self.close_counts.get(close, 0) + 1
        telemetry.counter(
            "serving_batches_total",
            "batches closed, by close cause (size-full vs deadline)",
            labels=("program", "close")).labels(
                program=self._label, close=close).inc()
        return batch, close

    def _execute(self, batch: List[_Request], close: str):
        pop_t = time.monotonic()
        live: List[_Request] = []
        for req in batch:
            if req.deadline_t is not None and pop_t > req.deadline_t:
                # deadline-aware shedding: the answer would arrive after
                # the client stopped waiting — don't spend device time
                with self._cond:
                    self._shed_locked("deadline")
                if req.span is not None:
                    req.span.end(outcome="shed", reason="deadline")
                req.future.set_exception(ServingOverloadError(
                    f"deadline expired after "
                    f"{(pop_t - req.submit_t) * 1e3:.1f}ms in queue",
                    reason="deadline", queue_depth=len(self._queue)))
            else:
                live.append(req)
        if not live:
            return
        feed = {name: np.concatenate(
                    [np.asarray(r.feed[name]) for r in live], axis=0)
                for name in self.engine.feed_names}
        # phase marks: run_batch fills (start, end) monotonic pairs for
        # pad / bucket_select / compute so per-request child spans can be
        # recorded retroactively without a second clock on the hot path
        marks = ({} if any(r.span is not None and r.span.sampled
                           for r in live) else None)
        try:
            fetch = self.engine.run_batch(feed, _phase_marks=marks)
        except BaseException as e:  # scatter the failure, keep serving
            for req in live:
                self.slo_monitor.record(ok=False)
                if req.span is not None:
                    req.span.end(outcome="error",
                                 error=f"{type(e).__name__}: {e}")
                if not req.future.cancelled():
                    req.future.set_exception(e)
            return
        done_t = time.monotonic()
        hist = telemetry.histogram(
            "serving_request_seconds",
            "per-request latency by phase (queue wait / device compute / "
            "total)", labels=("program", "phase"))
        off = 0
        # every scatter child starts where the compute mark ended: the
        # slice/convert/set_result stretch after compute is delivery
        # latency from each request's point of view, even though the
        # batch scatters results one request at a time
        scatter_t = (marks or {}).get("compute",
                                      (done_t, done_t))[1]
        with self._cond:
            # one bulk update, not a bare += per request: stats() reads
            # `completed` under the condition from client threads
            self.completed += len(live)
        for req in live:
            out = [f[off:off + req.rows] for f in fetch]
            off += req.rows
            req.future.set_result(out)
            hist.labels(program=self._label, phase="queue").observe(
                pop_t - req.submit_t)
            hist.labels(program=self._label, phase="compute").observe(
                done_t - pop_t)
            hist.labels(program=self._label, phase="total").observe(
                done_t - req.submit_t)
            self.slo_monitor.record(ok=True,
                                    latency_s=done_t - req.submit_t)
            if req.span is not None and req.span.sampled:
                self._record_children(
                    req, pop_t, done_t, marks or {}, close, scatter_t)

    def _record_children(self, req: _Request, pop_t: float, done_t: float,
                         marks: Dict, close: str,
                         scatter_start: float) -> float:
        """Record this request's queue/pad/bucket_select/compute/scatter
        children and end the parent span. The children tile the parent
        interval contiguously: queue ends at pop_t, pad absorbs the
        coalesce+validate+pad stretch up to the marks' pad end,
        bucket_select and compute come from the engine's marks, and
        scatter runs from the batch's compute end to the moment this
        request's result was delivered — so each request's child
        durations sum to its parent's within measurement noise (scatter
        children of co-batched requests overlap; they live in different
        traces)."""
        sp = req.span
        tracing.record_span("queue", req.submit_t, pop_t, parent=sp,
                            attrs={"close": close})
        pad = marks.get("pad")
        sel = marks.get("bucket_select")
        comp = marks.get("compute")
        pad_end = pad[1] if pad else pop_t
        tracing.record_span("pad", pop_t, pad_end, parent=sp,
                            attrs={"rows": req.rows})
        if sel:
            tracing.record_span("bucket_select", sel[0], sel[1],
                                parent=sp,
                                attrs={"bucket": marks.get("bucket")})
        if comp:
            tracing.record_span("compute", comp[0], comp[1], parent=sp,
                                attrs={"bucket": marks.get("bucket")})
        end_t = time.monotonic()
        tracing.record_span("scatter", scatter_start, end_t, parent=sp)
        sp.end(end=end_t, outcome="ok",
               bucket=marks.get("bucket"))
        return end_t

    # --- accounting ---------------------------------------------------------
    def _shed_locked(self, reason: str):
        self.shed += 1
        self.slo_monitor.record(ok=False)
        telemetry.counter(
            "serving_shed_total",
            "requests rejected by overload control, by cause",
            labels=("program", "reason")).labels(
                program=self._label, reason=reason).inc()

    def _depth_gauge_locked(self):
        telemetry.gauge(
            "serving_queue_depth",
            "requests waiting in the batcher queue",
            labels=("program",)).labels(program=self._label).set(
                len(self._queue))

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def stats(self) -> Dict[str, object]:
        with self._cond:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "queue_depth": len(self._queue),
                "close_counts": dict(self.close_counts),
                "goodput_fraction": (self.completed / self.submitted
                                     if self.submitted else 1.0),
            }
        out["slo"] = self.slo_monitor.report()
        return out
