"""Concurrent-client load harness for the serving stack.

Drives a `DynamicBatcher` with N client threads issuing back-to-back
requests and reports the numbers the ROADMAP's serving trajectory tracks:
p50/p99 end-to-end latency, throughput (qps), the bucket-hit
distribution from the engine's AOT cache, shed fraction, and goodput.
`overload_report` runs the canonical two-phase experiment — a normal
phase at N clients, then a 2x overload phase against a bounded queue —
showing the load-shedding policy holding accepted-request latency while
goodput (not availability) absorbs the excess. bench.py's
BENCH_MODE=serving and the `serve` CLI subcommand are thin wrappers over
these functions, so the JSON they emit comes from one implementation.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..errors import ServingOverloadError

_RESULT_TIMEOUT_S = 60.0


def run_load(batcher, make_feed: Callable[[int, int], Dict],
             clients: int = 4, requests_per_client: int = 8,
             deadline_ms: Optional[float] = None,
             label: str = "normal") -> Dict[str, object]:
    """Run `clients` threads, each submitting `requests_per_client`
    requests built by `make_feed(client_idx, request_idx)` and blocking on
    the future. Returns one phase payload with the serving trajectory
    keys (p50_ms/p99_ms/qps/shed_fraction/bucket_hits/goodput_fraction)."""
    engine = batcher.engine
    runs_before = dict(engine.bucket_runs)
    latencies_ms: List[float] = []
    ok = [0]
    shed = [0]
    timeouts = [0]
    errors = [0]
    lock = threading.Lock()

    def client(ci: int):
        for ri in range(requests_per_client):
            feed = make_feed(ci, ri)
            t0 = time.monotonic()
            try:
                fut = batcher.submit(feed, deadline_ms=deadline_ms)
                fut.result(timeout=_RESULT_TIMEOUT_S)
            except ServingOverloadError:
                with lock:
                    shed[0] += 1
                continue
            except _FutureTimeout:
                # a stuck future must not kill the client thread: count
                # the timeout outcome and keep issuing this client's
                # remaining requests
                with lock:
                    timeouts[0] += 1
                continue
            except Exception:
                # engine failure scattered onto the future — account it,
                # keep the load going
                with lock:
                    errors[0] += 1
                continue
            dt_ms = (time.monotonic() - t0) * 1e3
            with lock:
                ok[0] += 1
                latencies_ms.append(dt_ms)

    threads = [threading.Thread(target=client, args=(i,), daemon=True,
                                name=f"pd-serving-client-{i}")
               for i in range(clients)]
    # hang watchdog over the whole load phase (a wedged engine shows up
    # as a sentinel hang report, not a silent stuck join); no-op fast
    # path when the sentinel is off
    from .. import sentinel as sentinel_mod
    _tok = sentinel_mod.arm_dispatch(f"serving_load:{label}")
    t0 = time.monotonic()
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sentinel_mod.disarm_dispatch(_tok)
    wall_s = max(time.monotonic() - t0, 1e-9)

    submitted = ok[0] + shed[0] + timeouts[0] + errors[0]
    bucket_hits = {
        str(b): engine.bucket_runs.get(b, 0) - runs_before.get(b, 0)
        for b in engine.buckets
        if engine.bucket_runs.get(b, 0) - runs_before.get(b, 0)}
    lat = np.asarray(latencies_ms, dtype=np.float64)
    payload = {
        "phase": label,
        "clients": clients,
        "requests": submitted,
        "p50_ms": float(np.percentile(lat, 50)) if lat.size else None,
        "p99_ms": float(np.percentile(lat, 99)) if lat.size else None,
        "qps": ok[0] / wall_s,
        "shed_fraction": shed[0] / submitted if submitted else 0.0,
        "goodput_fraction": ok[0] / submitted if submitted else 1.0,
        "timeouts": timeouts[0],
        "errors": errors[0],
        "bucket_hits": bucket_hits,
        "wall_s": wall_s,
    }
    # the telemetry path to the same percentiles (bucket-resolution): kept
    # in the payload so dashboards reading only metric series agree with
    # the harness's exact ones on rank ordering
    q50 = telemetry.histogram_quantile(
        "serving_request_seconds", 0.5,
        program=getattr(engine, "_label", "p?"), phase="total")
    q99 = telemetry.histogram_quantile(
        "serving_request_seconds", 0.99,
        program=getattr(engine, "_label", "p?"), phase="total")
    payload["telemetry_p50_ms"] = q50 * 1e3 if q50 is not None else None
    payload["telemetry_p99_ms"] = q99 * 1e3 if q99 is not None else None
    return payload


def overload_report(batcher, make_feed, clients: int = 4,
                    requests_per_client: int = 8,
                    deadline_ms: Optional[float] = None) -> Dict[str, object]:
    """The two-phase serving experiment: a normal phase at N clients, then
    an overload phase at 2N clients with a per-request deadline, against
    the batcher's bounded queue. The overload phase is expected to shed
    (shed_fraction > 0 under real pressure) while accepted requests keep
    completing — goodput degrades gracefully instead of latency
    collapsing."""
    mon = getattr(batcher, "slo_monitor", None)
    normal = run_load(batcher, make_feed, clients=clients,
                      requests_per_client=requests_per_client,
                      deadline_ms=deadline_ms, label="normal")
    # evaluate burn before the overload phase starts so the "normal"
    # rates reflect only normal-phase traffic inside the windows
    slo_normal = mon.report() if mon is not None else None
    overload = run_load(batcher, make_feed, clients=2 * clients,
                        requests_per_client=requests_per_client,
                        deadline_ms=deadline_ms, label="overload")
    slo_overload = mon.report() if mon is not None else None
    slo = None
    if mon is not None:
        slo = {
            "objective": mon.slo.to_dict(),
            "normal": {w: slo_normal["windows"][w]["burn_rate"]
                       for w in ("fast", "slow")},
            "overload": {w: slo_overload["windows"][w]["burn_rate"]
                         for w in ("fast", "slow")},
            "windows": slo_overload["windows"],
        }
    return {
        "normal": normal,
        "overload": overload,
        "engine": batcher.engine.stats(),
        "batcher": batcher.stats(),
        "slo": slo,
    }
