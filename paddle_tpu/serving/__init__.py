"""Inference serving subsystem (reference: the fluid inference library +
capi GradientMachine, rebuilt TPU-natively).

Three layers, composed bottom-up:

  * `ServingEngine` (engine.py) — AOT program cache: prune to the
    inference fetch set, analyzer admission gate, one XLA executable per
    padded batch-size bucket (powers-of-two ladder, LRU-evicted),
    weights device-resident and donated across calls.
  * `DynamicBatcher` (batcher.py) — thread-safe queue coalescing
    variable-size requests into the smallest admissible bucket under a
    max-latency timer; bounded depth with deadline-aware load shedding
    (`ServingOverloadError`), per-request latency histograms.
  * harness.py — concurrent-client load generator reporting
    p50/p99/qps/bucket-hits/goodput; backs `BENCH_MODE=serving` and
    `python -m paddle_tpu serve`.
  * slo.py — per-model availability/latency objectives with fast/slow
    window burn-rate evaluation, fed one outcome per request by the
    batcher and scraped via `slo_burn_rate{model,window}` / `/healthz`.
"""

from .engine import (ServingEngine, bucket_ladder, is_training_only_op,
                     training_only_op_types)
from .batcher import DynamicBatcher
from .harness import overload_report, run_load
from .slo import SLO, SLOMonitor, monitor_for
from ..errors import ServingOverloadError

__all__ = ["ServingEngine", "DynamicBatcher", "ServingOverloadError",
           "bucket_ladder", "is_training_only_op", "training_only_op_types",
           "overload_report", "run_load", "SLO", "SLOMonitor",
           "monitor_for"]
