"""Central flag registry (reference: paddle/utils/Flags.cpp's 28 gflags +
the fluid DEFINE_* flags scattered near use, forwarded via
core.init_gflags). Flags are declared here with defaults/help, read from
`PADDLE_TPU_<NAME>` environment variables (the TPU-native analogue of
gflags' --name=value), and queryable at runtime:

    from paddle_tpu import flags
    flags.get("check_nan_inf")      # -> bool
    flags.set("check_nan_inf", True)  # runtime toggle (writes the env var)
    flags.dump()                    # -> {name: (value, help)}

Most modules keep reading their flags at import time for zero overhead; this
registry is the single catalogue of what exists (reference Flags.cpp role).
A growing set of flags is *live* — re-read through get() on every use, so
set() changes behavior at runtime: `vlog`, `check_nan_inf`,
`nonfinite_attribution`, `flight_recorder` (executor.py / inspector.py).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

_REGISTRY: Dict[str, Tuple[Any, type, str]] = {}
# value seen when this module was imported: consuming modules read their
# PADDLE_TPU_* vars at import time, so THIS is what live code is acting on
_IMPORT_SNAPSHOT: Dict[str, Any] = {}


def define(name: str, default, help_str: str, type_=None):
    t = type_ or type(default)
    _REGISTRY[name] = (default, t, help_str)
    _IMPORT_SNAPSHOT[name] = get(name)
    return _IMPORT_SNAPSHOT[name]


def _parse(raw: str, t: type, default):
    # match exactly how the modules read their env vars: bool flags are on
    # only for "1" (executor.py etc. test == "1"), numeric flags tolerate
    # an empty value by falling back to the default
    if t is bool:
        return raw == "1"
    if raw == "":
        return default
    return t(raw)


def get(name: str):
    """Current environment value. NOTE: most consuming modules snapshot
    their flag at import time, so an env var changed after import shows
    here without changing live behavior — compare against snapshot()."""
    default, t, _ = _REGISTRY[name]
    raw = os.environ.get(f"PADDLE_TPU_{name.upper()}")
    if raw is None:
        return default
    return _parse(raw, t, default)


def set(name: str, value):
    """Set a flag at runtime by writing its `PADDLE_TPU_<NAME>` env var (so
    child processes inherit it, matching how gflags values propagate through
    the environment in the reference's distributed launchers). Live-read
    flags (vlog, check_nan_inf, nonfinite_attribution, flight_recorder)
    react immediately; import-snapshot consumers keep their old value —
    dump() annotates the divergence. `value=None` unsets the env var,
    restoring the registered default. Returns the new effective value."""
    default, t, _ = _REGISTRY[name]
    env = f"PADDLE_TPU_{name.upper()}"
    if value is None:
        os.environ.pop(env, None)
        return default
    if t is bool:
        raw = "1" if value not in (False, 0, "0", "") else "0"
    else:
        raw = str(t(value))
    os.environ[env] = raw
    return get(name)


def snapshot(name: str):
    """The value at import time — what live modules are actually using."""
    return _IMPORT_SNAPSHOT[name]


def dump() -> Dict[str, Tuple[Any, str]]:
    """{name: (value, help)}; when the current env differs from the
    import-time snapshot the help is annotated, since live modules act on
    the snapshot, not the new env value."""
    out = {}
    for n, (_, _, h) in sorted(_REGISTRY.items()):
        cur = get(n)
        if cur != _IMPORT_SNAPSHOT.get(n, cur):
            h = (f"{h} [env changed after import: active="
                 f"{_IMPORT_SNAPSHOT[n]!r}, env={cur!r}]")
        out[n] = (cur, h)
    return out


# --- the catalogue (reference Flags.cpp / executor.cc DEFINE_bool etc.) ----
define("eager", False,
       "op-by-op interpretation instead of whole-block jit "
       "(reference executor.cc interpreter semantics; debugging)")
define("check_nan_inf", False,
       "scan op outputs for NaN/Inf each step "
       "(reference FLAGS_check_nan_inf, executor.cc:325)")
define("trap_fp", False,
       "raise at the op producing NaN/Inf via jax debug-nans "
       "(reference TrainerMain.cpp:49 feenableexcept)")
define("benchmark", False,
       "eager mode: wait for device completion after every op and log "
       "per-op wall time (reference FLAGS_benchmark, executor.cc:321)")
define("allow_zero_grad", False,
       "permit NO_GRAD ops with differentiable inputs on the loss path "
       "instead of raising (append_backward safety check)")
define("vlog", 0,
       "verbose logging level; >0 enables paddle_tpu.vlog output "
       "(reference glog VLOG levels)")
define("record_ops", "",
       "file path: append every executed op type (tools/op_coverage.py)")
define("test_platform", "cpu",
       "jax platform the test suite forces (tests/conftest.py)")
define("xla_cache", "",
       "persistent XLA compilation cache dir override (tests/conftest.py)")
define("max_loop_iters", 128,
       "default while-loop step-scope recording capacity "
       "(While(max_iters=...) overrides per loop)")
define("nonfinite_attribution", True,
       "on NaN/Inf detection, replay the step with bisection probes to "
       "name the first offending op (inspector.attribute_nonfinite); "
       "live-read, 0 disables the extra replay runs")
define("flight_recorder", "",
       "path: enable the inspector flight recorder; a JSON crash report "
       "is written there on executor exception or fatal signal "
       "(inspector.enable_flight_recorder)")
define("step_log", "",
       "JSONL step-event log path (telemetry.enable_step_log; read back "
       "with telemetry.read_step_log / the `telemetry` CLI)")
define("telemetry_fetch", True,
       "fetch program._telemetry_fetch_extra side-outputs (e.g. the clip "
       "pass's global norm) alongside user fetches; 0 skips the per-step "
       "device->host read for latency-critical loops")
