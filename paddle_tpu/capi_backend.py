"""Python side of the C inference API (native/capi.cc embeds CPython and
drives this module; reference: paddle/capi/gradient_machine.h fronted the
C++ GradientMachine the same way).

Machine wraps load_inference_model + a private scope; inputs arrive as raw
float32 bytes + dims from C, outputs go back the same way."""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


class Machine:
    def __init__(self, model_dir: str):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod

        self._fluid = fluid
        self._executor_mod = executor_mod
        self._scope = executor_mod.Scope()
        self._exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(self._scope):
            (self._program, self._feed_names,
             self._fetch_targets) = fluid.io.load_inference_model(
                model_dir, self._exe)
        self._inputs: Dict[str, np.ndarray] = {}

    def set_input(self, name: str, payload: bytes, dims: Tuple[int, ...]):
        if name not in self._feed_names:
            raise KeyError(
                f"'{name}' is not a feed of this model; feeds: "
                f"{self._feed_names}")
        arr = np.frombuffer(payload, dtype=np.float32).reshape(dims).copy()
        self._inputs[name] = arr

    def forward(self) -> List[Tuple[bytes, Tuple[int, ...]]]:
        missing = [n for n in self._feed_names if n not in self._inputs]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        with self._executor_mod.scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(self._inputs),
                                 fetch_list=self._fetch_targets)
        result = []
        for o in outs:
            a = np.ascontiguousarray(np.asarray(o), dtype=np.float32)
            result.append((a.tobytes(), tuple(int(d) for d in a.shape)))
        return result
