"""Python side of the C inference API (native/capi.cc embeds CPython and
drives this module; reference: paddle/capi/gradient_machine.h fronted the
C++ GradientMachine the same way, with paddle_arguments carrying value
matrices, integer id vectors, and sequence_start_positions).

Machine is now a thin handle over `serving.ServingEngine` — the C API's
create/feed/fetch/destroy lifecycle maps onto engine construction
(load_inference_model into a private scope + AOT bucket cache),
`engine.infer` (dense inputs ride the bucketed AOT executables; LoD
inputs fall back to the classic executor on the same pruned program, as
`serving_fallback_total{reason="lod"}` records), and `destroy()`
(drop executables + resident device state). Inputs arrive as raw bytes +
dims + dtype tag from C (0=f32, 1=i64, 2=i32 — capi.h paddle_tpu_dtype),
optional level-1 LoD offsets attach per input, outputs go back as float32
bytes."""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32}


class Machine:
    def __init__(self, model_dir: str):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        from paddle_tpu.serving import ServingEngine

        self._executor_mod = executor_mod
        self._engine = ServingEngine(model_dir,
                                     place=fluid.CPUPlace())
        self._feed_names = list(self._engine.feed_names)
        self._inputs: Dict[str, np.ndarray] = {}
        self._lods: Dict[str, list] = {}

    def set_input(self, name: str, payload: bytes, dims: Tuple[int, ...],
                  dtype: int = 0):
        if self._engine.closed:
            raise RuntimeError("Machine has been destroyed")
        if name not in self._feed_names:
            raise KeyError(
                f"'{name}' is not a feed of this model; feeds: "
                f"{self._feed_names}")
        np_dtype = _DTYPES[int(dtype)]
        arr = np.frombuffer(payload, dtype=np_dtype).reshape(dims).copy()
        self._inputs[name] = arr
        self._lods.pop(name, None)

    def set_input_lod(self, name: str, offsets: Tuple[int, ...]):
        if name not in self._inputs:
            raise KeyError(f"set_input must stage '{name}' before its LoD")
        offs = [int(o) for o in offsets]
        rows = self._inputs[name].shape[0]
        if offs[-1] != rows:
            raise ValueError(
                f"LoD offsets end at {offs[-1]} but '{name}' has {rows} "
                "rows (offsets are sequence_start_positions over axis 0)")
        self._lods[name] = offs

    def forward(self) -> List[Tuple[bytes, Tuple[int, ...]]]:
        missing = [n for n in self._feed_names if n not in self._inputs]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        feed = {}
        for n, arr in self._inputs.items():
            if n in self._lods:
                feed[n] = self._executor_mod.LoDTensor(arr, [self._lods[n]])
            else:
                feed[n] = arr
        outs = self._engine.infer(feed)
        result = []
        for o in outs:
            a = np.ascontiguousarray(np.asarray(o), dtype=np.float32)
            result.append((a.tobytes(), tuple(int(d) for d in a.shape)))
        return result

    def destroy(self):
        """paddle_tpu_machine_destroy: release executables + device state.
        Idempotent; further set_input/forward calls raise."""
        self._engine.close()
        self._inputs.clear()
        self._lods.clear()

    @property
    def engine(self):
        """The backing ServingEngine (bucket/cache stats for tests)."""
        return self._engine
