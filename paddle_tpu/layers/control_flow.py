"""Control-flow DSL (reference: python/paddle/fluid/layers/control_flow.py:
While :607, StaticRNN :382, DynamicRNN :1349, IfElse :1247, Switch :1158,
ConditionalBlock :1101, array ops :888-1058, increment, less_than).

Sub-blocks are real IR blocks; the executor lowers them with
lax.while_loop / lax.cond / lax.scan (ops/control_flow_ops.py)."""

from __future__ import annotations

import contextlib
from typing import List, Optional

from ..framework.desc import BlockRef, VarType
from ..framework.framework import (Variable, default_main_program)
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = [
    "While", "StaticRNN", "DynamicRNN", "IfElse", "Switch",
    "ConditionalBlock", "array_read", "array_write", "array_length",
    "create_array", "increment", "less_than", "equal", "zeros_like",
    "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
    "array_to_lod_tensor", "split_lod_tensor", "merge_lod_tensor",
    "reorder_lod_tensor_by_rank", "shrink_memory", "Print",
]


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Execution-time tensor logging (reference control_flow.py:149 Print
    over print_op.cc): returns a pass-through of `input` that prints the
    message + value every time the step runs — under jit via
    jax.debug.print, so it works inside the compiled block. The
    formatting flags are accepted for parity; name/shape/dtype are always
    shown, `summarize` truncates the printed values."""
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or "",
                            "summarize": summarize,
                            "first_n": first_n,
                            "print_phase": print_phase})
    return out


def increment(x, value=1.0, in_place=True):
    """x += value (reference control_flow.py increment)."""
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def less_than(x, y, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
    helper.append_op(type="less_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
    helper.append_op(type="equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


# --- tensor arrays ----------------------------------------------------------

def create_array(dtype):
    """Create a LOD_TENSOR_ARRAY var (reference control_flow.py:888)."""
    helper = LayerHelper("array")
    return helper.main_program.current_block().create_var(
        name=None, dtype=dtype, type=VarType.LOD_TENSOR_ARRAY)


def array_write(x, i, array=None, capacity=None):
    """array[i] = x (reference control_flow.py array_write)."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    attrs = {}
    if capacity is not None:
        attrs["capacity"] = int(capacity)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i], "Out": [array]},
                     outputs={"Out": [array]}, attrs=attrs)
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(dtype=array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


# --- While ------------------------------------------------------------------

class While:
    """while (cond) { ... } (reference control_flow.py:607).

    cond must be a bool Variable; every loop-state var (anything assigned in
    the body that must survive iterations, including cond) must hold a value
    before the loop starts.

    max_iters bounds the step-scope recording used by while_grad (default
    128); a training loop that exceeds it gets NaN-poisoned gradients, so
    raise it to the true iteration bound for long loops.
    """

    def __init__(self, cond, name=None, max_iters=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.max_iters = max_iters

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program.create_block()
        try:
            yield
        finally:
            program.rollback()

        # reads: consumed names not produced inside; writes: produced names
        # that already exist in the parent chain (loop state)
        produced, reads, writes = set(), [], []
        for op_ in sub_block.ops:
            for n in op_.input_arg_names:
                if n not in produced and n not in reads:
                    reads.append(n)
            for n in op_.output_arg_names:
                produced.add(n)
                if parent_block.has_var_recursive(n) and n not in writes:
                    writes.append(n)
        x_names = [n for n in reads
                   if parent_block.has_var_recursive(n)]
        attrs = {"sub_block": BlockRef(sub_block.idx)}
        if self.max_iters is not None:
            attrs["max_loop_iters"] = int(self.max_iters)
        parent_block.append_op(
            type="while",
            inputs={"Condition": [self.cond_var.name], "X": x_names},
            outputs={"Out": writes},
            attrs=attrs)


# --- ConditionalBlock / IfElse / Switch -------------------------------------

class ConditionalBlock:
    """Guarded block (reference control_flow.py:1101)."""

    def __init__(self, inputs, is_scalar_condition=False, name=None):
        for each_input in inputs:
            assert isinstance(each_input, Variable)
        self.inputs = inputs
        self.is_scalar_condition = is_scalar_condition
        self.helper = LayerHelper("conditional_block", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        out_names, produced, reads = [], set(), []
        for op_ in sub_block.ops:
            for n in op_.input_arg_names:
                if n not in produced and n not in reads:
                    reads.append(n)
            for n in op_.output_arg_names:
                produced.add(n)
                if n not in out_names:
                    out_names.append(n)
        # explicit reads (weights, pre-existing outputs) so grads flow to
        # them through conditional_block_grad instead of being closure
        # constants under vjp
        x_names = [n for n in reads if parent_block.has_var_recursive(n)]
        for n in out_names:
            if parent_block.has_var_recursive(n) and n not in x_names:
                x_names.append(n)
        parent_block.append_op(
            type="conditional_block",
            inputs={"Cond": [v.name for v in self.inputs], "X": x_names},
            outputs={"Out": out_names},
            attrs={"sub_block": BlockRef(sub_block.idx),
                   "is_scalar_condition": self.is_scalar_condition})


class IfElse:
    """if/else over a batch-wise bool condition (reference
    control_flow.py:1247). The reference scatters true/false rows into
    sub-blocks; the dense lowering evaluates both branches on the full batch
    and selects rows by the condition mask."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.in_else = False
        self.true_outs: List[Variable] = []
        self.false_outs: List[Variable] = []

    def input(self, x):
        # dense lowering: both branches see the full input
        return x

    @contextlib.contextmanager
    def true_block(self):
        self.in_else = False
        yield

    @contextlib.contextmanager
    def false_block(self):
        self.in_else = True
        yield

    def output(self, *outs):
        target = self.false_outs if self.in_else else self.true_outs
        target.extend(outs)

    def __call__(self):
        assert len(self.true_outs) == len(self.false_outs), (
            "IfElse needs output() in both branches")
        from . import nn as nn_layers
        results = []
        for t, f in zip(self.true_outs, self.false_outs):
            helper = LayerHelper("ifelse_select")
            out = helper.create_tmp_variable(dtype=t.dtype)
            helper.append_op(type="select_rows_by_cond",
                             inputs={"Cond": [self.cond], "X": [t],
                                     "Y": [f]},
                             outputs={"Out": [out]})
            results.append(out)
        return results if len(results) > 1 else results[0]


class Switch:
    """switch/case on scalar conditions (reference control_flow.py:1158);
    used for LR warmup schedules. Each case assigns to pre-created vars."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions: List[Variable] = []

    @contextlib.contextmanager
    def case(self, condition):
        from . import ops as ops_layers
        if not self.pre_not_conditions:
            cond = condition
        else:
            pre = self.pre_not_conditions[-1]
            cond = _logical_and(pre, condition)
        not_cond = _logical_not(condition) if not self.pre_not_conditions \
            else _logical_and(self.pre_not_conditions[-1],
                              _logical_not(condition))
        self.pre_not_conditions.append(not_cond)
        cb = ConditionalBlock([cond], is_scalar_condition=True)
        with cb.block():
            yield

    @contextlib.contextmanager
    def default(self):
        assert self.pre_not_conditions, "default must follow a case"
        cb = ConditionalBlock([self.pre_not_conditions[-1]],
                              is_scalar_condition=True)
        with cb.block():
            yield

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def _logical_and(x, y):
    helper = LayerHelper("logical_and")
    out = helper.create_tmp_variable(dtype="bool")
    helper.append_op(type="logical_and", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def _logical_not(x):
    helper = LayerHelper("logical_not")
    out = helper.create_tmp_variable(dtype="bool")
    helper.append_op(type="logical_not", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


# --- RNNs -------------------------------------------------------------------

class _RNNBase:
    """Shared machinery for StaticRNN/DynamicRNN: build a step sub-block,
    then emit one `rnn` op lowered to lax.scan."""

    def __init__(self, kind: str, is_reverse=False, name=None):
        self.helper = LayerHelper(kind, name=name)
        self.is_reverse = is_reverse
        self.seq_inputs: List[Variable] = []       # outer [B,T,...] vars
        self.step_input_vars: List[Variable] = []  # block-local [B,...] vars
        self.init_states: List[Variable] = []
        self.state_vars: List[Variable] = []
        self.state_out_vars: List[Optional[Variable]] = []
        self.step_output_vars: List[Variable] = []
        self.outputs: List[Variable] = []
        self.sub_block = None
        self.parent_block = None
        self._status = "outside"

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        self.parent_block = program.current_block()
        self.sub_block = program.create_block()
        self._status = "in"
        try:
            yield
        finally:
            self._status = "done"
            program.rollback()

        assert self.step_input_vars, "RNN needs step_input()"
        assert all(v is not None for v in self.state_out_vars), (
            "every memory needs update_memory()")
        outs = []
        for sv in self.step_output_vars:
            o = self.parent_block.create_var(
                name=None, dtype=sv.dtype,
                shape=[sv.shape[0], None] + list(sv.shape[1:])
                if sv.shape else None)
            outs.append(o)
        finals = []
        for st in self.state_out_vars:
            f = self.parent_block.create_var(name=None, dtype=st.dtype,
                                             shape=st.shape)
            finals.append(f)
        # Outer vars the step block reads (weights, encoder states, …) become
        # explicit op inputs so gradients flow to them — a closure-captured
        # read would be a constant under jax.vjp and silently never train.
        inner = {v.name for v in self.step_input_vars} | \
                {v.name for v in self.state_vars}
        produced = set()
        extra = []
        for op_ in self.sub_block.ops:
            for n in op_.input_arg_names:
                if n in inner or n in produced or n in extra:
                    continue
                if self.parent_block.has_var_recursive(n):
                    extra.append(n)
            for n in op_.output_arg_names:
                produced.add(n)
        self.parent_block.append_op(
            type="rnn",
            inputs={"Inputs": [v.name for v in self.seq_inputs],
                    "InitStates": [v.name for v in self.init_states],
                    "ExtraIn": extra},
            outputs={"Outputs": [v.name for v in outs],
                     "FinalStates": [v.name for v in finals]},
            attrs={"sub_block": BlockRef(self.sub_block.idx),
                   "step_input_vars": [v.name for v in self.step_input_vars],
                   "state_vars": [v.name for v in self.state_vars],
                   "state_out_vars": [v.name for v in self.state_out_vars],
                   "step_output_vars": [v.name for v in self.step_output_vars],
                   "extra_in_vars": extra,
                   "is_reverse": self.is_reverse})
        self.outputs = outs
        self.final_states = finals

    def step_input(self, x):
        """Register x [B,T,...] as a sequence input; returns the per-step
        view [B,...] usable inside the block."""
        assert self._status == "in"
        self.seq_inputs.append(x)
        step = self.sub_block.create_var(
            name=None, dtype=x.dtype,
            shape=[x.shape[0]] + list(x.shape[2:]) if x.shape else None)
        self.step_input_vars.append(step)
        return step

    def memory(self, init=None, shape=None, value=0.0, dtype="float32",
               batch_ref=None):
        """Loop-carried state. init: Variable holding the initial value; or
        shape+value to fill a constant (batch size taken from batch_ref or
        the first step input's batch dim)."""
        assert self._status == "in"
        if init is None:
            assert shape is not None
            ref = batch_ref if batch_ref is not None else self.seq_inputs[0]
            # build the init in the PARENT block (it runs before the loop)
            from ..framework.framework import in_block
            with in_block(self.helper.main_program, self.parent_block.idx):
                init = tensor_layers.fill_constant_batch_size_like(
                    input=ref, shape=[-1] + list(shape), dtype=dtype,
                    value=value)
        mem = self.sub_block.create_var(name=None, dtype=init.dtype,
                                        shape=init.shape)
        self.init_states.append(init)
        self.state_vars.append(mem)
        self.state_out_vars.append(None)
        return mem

    def update_memory(self, mem, new_val):
        assert self._status == "in"
        idx = self.state_vars.index(mem)
        self.state_out_vars[idx] = new_val

    def step_output(self, o):
        assert self._status == "in"
        self.step_output_vars.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        outs = self.outputs
        return outs if len(outs) > 1 else outs[0]


class StaticRNN(_RNNBase):
    """Fixed-length RNN (reference control_flow.py:382). On the padded
    convention it is the same scan as DynamicRNN; masking simply sees
    full-length sequences."""

    def __init__(self, name=None):
        super().__init__("static_rnn", name=name)

    @contextlib.contextmanager
    def step(self):
        with self.block():
            yield


class DynamicRNN(_RNNBase):
    """Variable-length RNN (reference control_flow.py:1349). The reference
    sorts sequences by length (lod_rank_table) and shrinks the batch each
    step; the padded lowering keeps the batch dense and masks by length —
    identical math, MXU-friendly shapes."""

    def __init__(self, name=None):
        super().__init__("dynamic_rnn", name=name)


# --- LoD-array plumbing (reference control_flow.py:665,888-1058) --------------

def lod_rank_table(x, level=0):
    """Sorted (index, length) table over a sequence batch (reference
    control_flow.py:665, lod_rank_table.cc); in the padded lowering this is
    the lengths vector riding the @SEQLEN channel."""
    helper = LayerHelper("lod_rank_table")
    table = helper.create_tmp_variable("int32")
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [table]}, attrs={"level": level})
    return table


def max_sequence_len(rank_table):
    """Max sequence length from a rank table (reference control_flow.py:704)."""
    helper = LayerHelper("max_sequence_len")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]}, attrs={})
    return out


def lod_tensor_to_array(x, table):
    """Split a sequence batch into a time-major TensorArray (reference
    control_flow.py:888)."""
    helper = LayerHelper("lod_tensor_to_array")
    array = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [array]}, attrs={})
    return array


def array_to_lod_tensor(x, table):
    """Stack a TensorArray back into a padded sequence batch (reference
    control_flow.py:919)."""
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [out]}, attrs={})
    return out


def split_lod_tensor(input, mask, level=0):
    """Row-route a batch by boolean mask (reference control_flow.py:943).
    Returns (in_true, in_false); dense lowering keeps row positions."""
    helper = LayerHelper("split_lod_tensor")
    out_true = helper.create_tmp_variable(input.dtype)
    out_false = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="split_lod_tensor",
                     inputs={"X": [input], "Mask": [mask]},
                     outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
                     attrs={"level": level})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    """Inverse of split_lod_tensor (reference control_flow.py:997)."""
    helper = LayerHelper("merge_lod_tensor")
    out = helper.create_tmp_variable(in_true.dtype)
    helper.append_op(type="merge_lod_tensor",
                     inputs={"InTrue": [in_true], "InFalse": [in_false],
                             "Mask": [mask], "X": [x]},
                     outputs={"Out": [out]}, attrs={"level": level})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder sequences into rank-table (descending length) order
    (reference control_flow.py:1058)."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]}, attrs={})
    return out


def shrink_memory(x, i, table):
    """Batch-shrink an RNN state to live sequences (reference
    control_flow.py:732, shrink_rnn_memory_op.cc); dense lowering is a
    pass-through — masking in the scan supplies the semantics."""
    helper = LayerHelper("shrink_memory")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]}, attrs={})
    return out
