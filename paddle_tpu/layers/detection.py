"""SSD detection layers DSL (reference: python/paddle/fluid/layers/
detection.py — detection_output :46, detection_map :157, bipartite_match
:208, target_assign :278, ssd_loss :350, multi_box_head :568, prior_box
via multi_box_head).

Same five-step SSD loss pipeline as the reference (match -> mine -> assign
-> loc/conf losses -> weighted sum), composed over the padded-batch
detection ops instead of LoD row routing: gt boxes/labels arrive as padded
[B, G, ...] + @SEQLEN, every per-prior target is a dense gather, and the
whole loss fuses into the model's XLA computation."""

from __future__ import annotations

import numpy as np

import math

from ..framework.framework import Variable
from ..layer_helper import LayerHelper
from . import control_flow as cf_layers
from . import nn
from . import ops as ops_layers
from . import tensor as tensor_layers

__all__ = [
    "prior_box", "iou_similarity", "box_coder", "bipartite_match",
    "target_assign", "mine_hard_examples", "ssd_loss", "detection_output",
    "multiclass_nms", "detection_map", "multi_box_head",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None,
              offset=0.5, name=None):
    """SSD prior boxes for one feature map (reference detection.py
    multi_box_head internals, prior_box_op.h). Returns (boxes [H, W, np, 4],
    variances same shape)."""
    helper = LayerHelper("prior_box")
    boxes = helper.create_tmp_variable("float32")
    variances = helper.create_tmp_variable("float32")
    # priors are coordinate constants derived from the feature-map SHAPE
    # (reference prior_box_op registers no grad); stating that here keeps
    # the silent-zero-grad check quiet about the feature-map input
    boxes.stop_gradient = True
    variances.stop_gradient = True
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
               "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    return boxes, variances


def iou_similarity(x, y):
    helper = LayerHelper("iou_similarity")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", name=None):
    helper = LayerHelper("box_coder")
    out = helper.create_tmp_variable(target_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": [prior_box],
                             "PriorBoxVar": [prior_box_var],
                             "TargetBox": [target_box]},
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy bipartite matching on a distance matrix (reference
    detection.py:208). Returns (match_indices [B, P], match_distance)."""
    helper = LayerHelper("bipartite_match")
    match_indices = helper.create_tmp_variable("int32")
    match_distance = helper.create_tmp_variable(dist_matrix.dtype)
    helper.append_op(type="bipartite_match",
                     inputs={"DistMat": [dist_matrix]},
                     outputs={"ColToRowMatchIndices": [match_indices],
                              "ColToRowMatchDist": [match_distance]},
                     attrs={"match_type": match_type,
                            "dist_threshold": dist_threshold})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Assign per-prior targets by match index (reference detection.py:278).
    Returns (out, out_weight)."""
    helper = LayerHelper("target_assign")
    out = helper.create_tmp_variable(input.dtype)
    out_weight = helper.create_tmp_variable("float32")
    # assigned targets are training CONSTANTS (the reference registers no
    # grad for target_assign; loc/conf loss grads flow only through the
    # predictions) — marking them stop_gradient states that intent so the
    # silent-zero-grad check in append_backward doesn't flag them
    out.stop_gradient = True
    out_weight.stop_gradient = True
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [out_weight]},
                     attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=1.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=0):
    helper = LayerHelper("mine_hard_examples")
    neg_indices = helper.create_tmp_variable("int32")
    updated = helper.create_tmp_variable("int32")
    inputs = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
              "MatchDist": [match_dist]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss]
    helper.append_op(type="mine_hard_examples", inputs=inputs,
                     outputs={"NegIndices": [neg_indices],
                              "UpdatedMatchIndices": [updated]},
                     attrs={"neg_pos_ratio": neg_pos_ratio,
                            "neg_dist_threshold": neg_dist_threshold,
                            "mining_type": mining_type,
                            "sample_size": sample_size})
    return neg_indices, updated


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.01,
                   nms_top_k=-1, nms_threshold=0.3, keep_top_k=-1,
                   nms_eta=1.0, name=None):
    helper = LayerHelper("multiclass_nms")
    out = helper.create_tmp_variable(bboxes.dtype)
    helper.append_op(type="multiclass_nms",
                     inputs={"BBoxes": [bboxes], "Scores": [scores]},
                     outputs={"Out": [out]},
                     attrs={"background_label": background_label,
                            "score_threshold": score_threshold,
                            "nms_top_k": nms_top_k,
                            "nms_threshold": nms_threshold,
                            "keep_top_k": keep_top_k, "nms_eta": nms_eta})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode predictions and run multi-class NMS (reference
    detection.py:46): loc [B, P, 4] codes, scores [B, P, C] (softmaxed
    here), priors [P, 4]. Returns padded [B, keep_top_k, 6] detections."""
    decoded = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                        target_box=loc, code_type="decode_center_size")
    scores = ops_layers.softmax(scores)
    scores = nn.transpose(scores, perm=[0, 2, 1])
    return multiclass_nms(bboxes=decoded, scores=scores,
                          background_label=background_label,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, nms_threshold=nms_threshold,
                          keep_top_k=keep_top_k, nms_eta=nms_eta)


def detection_map(detect_res, label, overlap_threshold=0.3,
                  evaluate_difficult=True, ap_version="integral",
                  background_label=0, class_num=None):
    """Batch mAP metric (reference detection.py:157, detection_map_op.h).
    detect_res padded [B, D, 6] (label, score, box); label padded [B, G, 6]
    (label, difficult, box)."""
    helper = LayerHelper("detection_map")
    map_out = helper.create_tmp_variable("float32")
    helper.append_op(type="detection_map",
                     inputs={"DetectRes": [detect_res], "Label": [label]},
                     outputs={"MAP": [map_out]},
                     attrs={"overlap_threshold": overlap_threshold,
                            "evaluate_difficult": evaluate_difficult,
                            "ap_type": ap_version,
                            "background_label": background_label})
    return map_out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (reference detection.py:350) — the five reference
    steps over padded batches:
      1. IoU(gt, priors) -> bipartite/per-prediction matching
      2. confidence loss on matched labels for mining
      3. hard-negative mining -> negative indices
      4. assign loc (encoded) + conf targets
      5. weighted smooth-L1 + softmax-CE, normalized by match count
    location [B, P, 4], confidence [B, P, C], gt_box padded LoD [B, G, 4],
    gt_label padded LoD [B, G, 1], prior_box [P, 4]. Returns [B, P, 1]
    per-prior weighted loss."""
    helper = LayerHelper("ssd_loss")
    if prior_box_var is None:
        pv_np = np.tile(np.array([0.1, 0.1, 0.2, 0.2], np.float32),
                        (prior_box.shape[0], 1))
        prior_box_var = tensor_layers.assign(pv_np)

    # 1. match
    iou = iou_similarity(x=gt_box, y=prior_box)
    matched_indices, matched_dist = bipartite_match(
        iou, match_type, overlap_threshold)

    # 2. conf loss on provisional targets, for mining
    gt_label_t, _ = target_assign(gt_label, matched_indices,
                                  mismatch_value=background_label)
    conf_loss_mine = nn.softmax_with_cross_entropy(confidence, gt_label_t)

    # 3. hard negative mining
    neg_indices, updated_indices = mine_hard_examples(
        cls_loss=conf_loss_mine, match_indices=matched_indices,
        match_dist=matched_dist, neg_pos_ratio=neg_pos_ratio,
        neg_dist_threshold=neg_overlap, mining_type=mining_type,
        sample_size=sample_size or 0)

    # 4. targets: encoded gt boxes per (gt, prior) pair, then per-prior picks
    encoded = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                        target_box=gt_box, code_type="encode_center_size")
    loc_target, loc_weight = target_assign(encoded, updated_indices,
                                           mismatch_value=0)
    conf_target, conf_weight = target_assign(
        gt_label, updated_indices, negative_indices=neg_indices,
        mismatch_value=background_label)

    # 5. losses
    conf_loss = nn.softmax_with_cross_entropy(confidence, conf_target)
    conf_loss = nn.elementwise_mul(conf_loss, conf_weight)

    diff = nn.elementwise_sub(location, loc_target)
    abs_diff = ops_layers.abs(diff)
    one = tensor_layers.fill_constant(shape=[1], dtype="float32", value=1.0)
    sq = nn.scale(nn.elementwise_mul(diff, diff), scale=0.5)
    lin = nn.scale(abs_diff, scale=1.0, bias=-0.5)
    is_small = nn.cast(cf_layers.less_than(abs_diff, one), "float32")
    is_big = nn.scale(is_small, scale=-1.0, bias=1.0)
    l1 = nn.elementwise_add(nn.elementwise_mul(sq, is_small),
                            nn.elementwise_mul(lin, is_big))
    loc_loss = nn.reduce_sum(l1, dim=-1, keep_dim=True)
    loc_loss = nn.elementwise_mul(loc_loss, loc_weight)

    loss = nn.elementwise_add(
        nn.scale(loc_loss, scale=loc_loss_weight),
        nn.scale(conf_loss, scale=conf_loss_weight))
    if normalize:
        denom = nn.reduce_sum(loc_weight)
        denom = nn.elementwise_max(
            denom, tensor_layers.fill_constant(shape=[1], dtype="float32",
                                               value=1.0))
        loss = nn.elementwise_div(loss, denom, axis=0)
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=None, flip=False, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None):
    """SSD detection head over multiple feature maps (reference
    detection.py:568): per-map loc/conf convs + prior boxes, concatenated.
    Returns (mbox_loc [B, total, 4], mbox_conf [B, total, C],
    boxes [total, 4], variances [total, 4])."""
    if min_sizes is None:
        # evenly spaced scales between min_ratio and max_ratio percent
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        if num_layer > 2:
            step = int(math.floor((max_ratio - min_ratio) / (num_layer - 2)))
            for ratio in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * ratio / 100.0)
                max_sizes.append(base_size * (ratio + step) / 100.0)
            min_sizes = [base_size * 0.1] + min_sizes
            max_sizes = [base_size * 0.2] + max_sizes
        else:
            min_sizes = [base_size * 0.2, base_size * 0.4]
            max_sizes = [base_size * 0.5, base_size * 0.8]

    locs, confs, prior_list, var_list = [], [], [], []
    for i, inp in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) else \
            [aspect_ratios[i]]
        st = steps[i] if steps else [step_w[i] if step_w else 0.0,
                                     step_h[i] if step_h else 0.0]
        if not isinstance(st, (list, tuple)):
            st = [st, st]
        boxes, variances = prior_box(
            inp, image, [ms] if not isinstance(ms, (list, tuple)) else ms,
            [mx] if mx and not isinstance(mx, (list, tuple)) else mx,
            ar, variance, flip, clip, st, offset)
        num_priors = boxes.shape[2]

        total = boxes.shape[0] * boxes.shape[1] * num_priors

        loc = nn.conv2d(inp, num_filters=num_priors * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        # [B, np*4, H, W] -> [B, H, W, np*4] -> [B, H*W*np, 4]
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn.reshape(loc, shape=[-1, total, 4])
        locs.append(loc)

        conf = nn.conv2d(inp, num_filters=num_priors * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn.reshape(conf, shape=[-1, total, num_classes])
        confs.append(conf)

        prior_list.append(nn.reshape(boxes, shape=[-1, 4]))
        var_list.append(nn.reshape(variances, shape=[-1, 4]))

    mbox_loc = tensor_layers.concat(locs, axis=1)
    mbox_conf = tensor_layers.concat(confs, axis=1)
    boxes = tensor_layers.concat(prior_list, axis=0)
    variances = tensor_layers.concat(var_list, axis=0)
    return mbox_loc, mbox_conf, boxes, variances
