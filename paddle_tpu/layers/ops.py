"""Auto-generated unary-op wrappers (reference: python/paddle/fluid/layers/
ops.py + layer_function_generator.py — ~40 wrappers from OpProtos)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "softshrink", "hard_shrink", "sqrt", "abs", "ceil", "floor", "round",
    "reciprocal", "log", "square", "softplus", "softsign", "brelu",
    "leaky_relu", "soft_relu", "elu", "relu6", "pow", "stanh",
    "hard_sigmoid", "swish", "thresholded_relu", "gelu", "silu",
    "softmax", "sign", "cumsum",
]

__all__ = list(_UNARY_OPS) + ["uniform_random", "gaussian_random",
                              "uniform_random_batch_size_like",
                              "gaussian_random_batch_size_like"]


def _make_unary(op_type):
    def f(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(dtype=x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out
    f.__name__ = op_type
    f.__doc__ = f"Elementwise {op_type} (reference activation_op.cc)."
    return f


for _name in _UNARY_OPS:
    globals()[_name] = _make_unary(_name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std, "seed": seed})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, seed=0, input_dim_idx=0,
                                   output_dim_idx=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="uniform_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype, "min": min,
                            "max": max, "seed": seed,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def gaussian_random_batch_size_like(input, shape, dtype="float32", mean=0.0,
                                    std=1.0, seed=0, input_dim_idx=0,
                                    output_dim_idx=0):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="gaussian_random_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype, "mean": mean,
                            "std": std, "seed": seed,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out
