"""Tensor-creation/manipulation layers
(reference: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

import numpy as np

from ..framework.framework import Variable, convert_dtype
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "assign", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "argmin", "argmax",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    from ..param_attr import ParamAttr
    attr = ParamAttr.to_attr(attr) if attr else ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(name=helper.name if name is None else name,
                                        dtype=dtype, shape=shape,
                                        persistable=persistable)
    from ..initializer import ConstantInitializer
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", input=input, name=name)
    out = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op(type="concat", inputs={"X": input},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum", input=input)
    if out is None:
        out = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_tmp_variable(dtype=input.dtype)
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_tmp_variable(dtype=input.dtype.name)
        helper.append_op(
            type="fill_constant_tensor", outputs={"Out": [output]},
            attrs={"shape": list(input.shape), "dtype": input.dtype.name,
                   "values": input.ravel().tolist()})
    else:
        raise TypeError("assign expects Variable or ndarray")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    out.stop_gradient = True
    out.desc.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    dtype = convert_dtype(dtype)
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    out.desc.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def _arg_min_max(x, op_type, axis):
    helper = LayerHelper(op_type)
    out = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    return _arg_min_max(x, "arg_min", axis)


def argmax(x, axis=0):
    return _arg_min_max(x, "arg_max", axis)
