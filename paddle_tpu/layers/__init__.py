"""Layers DSL (reference: python/paddle/fluid/layers/__init__.py)."""

from . import math_op_patch  # noqa: F401  (patches Variable operators)
from .io import *            # noqa: F401,F403
from .tensor import *        # noqa: F401,F403
from .nn import *            # noqa: F401,F403
from .sequence import *      # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from . import detection     # noqa: F401
from . import ops as _ops_module
from .ops import *           # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403

from . import (io, tensor, nn, ops, learning_rate_scheduler, sequence,  # noqa: F401
               control_flow)
