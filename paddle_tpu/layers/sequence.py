"""Sequence layers DSL (reference: python/paddle/fluid/layers/nn.py
dynamic_lstm :247, dynamic_lstmp :393, dynamic_gru :579, gru_unit :686,
lstm_unit :1935, sequence_conv, sequence_pool/first_step/last_step,
sequence_softmax, sequence_expand, sequence_reshape, lod_reset).

All sequence inputs/outputs use the padded-dense convention
(executor.pack_to_padded): [batch, T, D] with a lengths side channel."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = [
    "sequence_unfold", "sequence_mask", "sequence_fold", "context_project",
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit", "lstm_unit",
    "sequence_conv", "sequence_pool", "sequence_first_step",
    "sequence_last_step", "sequence_softmax", "sequence_expand",
    "sequence_reshape", "sequence_slice", "sequence_concat", "sequence_erase",
    "lod_reset",
]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LSTM over a padded sequence (reference nn.py:247 dynamic_lstm,
    lstm_op.cc). `input` is the pre-computed x-projection [B, T, 4H]
    (size = 4H); returns (hidden [B,T,H], cell [B,T,H])."""
    helper = LayerHelper("lstm", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    size = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 4 * size], dtype=dtype)
    bias_size = [1, 7 * size if use_peepholes else 4 * size]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(type="lstm", inputs=inputs,
                     outputs={"Hidden": [hidden], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with a recurrent projection layer (reference nn.py:393,
    lstmp_op.cc): hidden is projected to proj_size before recurrence.
    Emits a single fused `lstmp` op; returns
    (projection [B,T,P], cell [B,T,H])."""
    helper = LayerHelper("lstmp", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    size = size // 4
    # recurrent weight operates on the projected state: [P, 4H]
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[proj_size, 4 * size], dtype=dtype)
    proj_weight = helper.create_parameter(attr=helper.param_attr,
                                          shape=[size, proj_size], dtype=dtype)
    bias_size = [1, 7 * size if use_peepholes else 4 * size]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    projection = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    helper.append_op(type="lstmp",
                     inputs={"Input": [input], "Weight": [weight],
                             "ProjWeight": [proj_weight], "Bias": [bias]},
                     outputs={"Projection": [projection], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation})
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32"):
    """GRU over a padded sequence (reference nn.py:579, gru_op.cc).
    `input` is the x-projection [B, T, 3H] (size = H)."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    hidden = helper.create_tmp_variable(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(type="gru", inputs=inputs,
                     outputs={"Hidden": [hidden]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """Single GRU step (reference nn.py:686, gru_unit_op.cc). `input` is the
    x-projection [B, 3H] (size = 3H); `hidden` [B, H]. Returns
    (new_hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    size = size // 3
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True,
                                   default_initializer=ConstantInitializer(0.0))
    gate = helper.create_tmp_variable(dtype)
    reset_hidden_prev = helper.create_tmp_variable(dtype)
    updated_hidden = helper.create_tmp_variable(dtype)
    helper.append_op(type="gru_unit",
                     inputs={"Input": [input], "HiddenPrev": [hidden],
                             "Weight": [weight], "Bias": [bias]},
                     outputs={"Hidden": [updated_hidden],
                              "ResetHiddenPrev": [reset_hidden_prev],
                              "Gate": [gate]},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    return updated_hidden, reset_hidden_prev, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference nn.py:1935): projects [x_t, h_prev] to 4H
    gates with an fc, then applies the lstm_unit op. Returns (h, c)."""
    from . import nn as nn_layers
    from . import tensor as tensor_layers
    helper = LayerHelper("lstm_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = cell_t_prev.shape[-1]
    concat_out = tensor_layers.concat(input=[x_t, hidden_t_prev], axis=-1)
    fc_out = nn_layers.fc(input=concat_out, size=4 * size,
                          param_attr=param_attr, bias_attr=bias_attr)
    c = helper.create_tmp_variable(x_t.dtype)
    h = helper.create_tmp_variable(x_t.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    """Context-window convolution over time (reference nn.py sequence_conv,
    sequence_conv_op.cc)."""
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    dtype = input.dtype
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(attr=helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="sequence_conv",
                     inputs={"X": [input], "Filter": [filter_param]},
                     outputs={"Out": [pre_bias]},
                     attrs={"contextStride": filter_stride,
                            "contextStart": -int(filter_size // 2),
                            "contextLength": filter_size})
    # output is [B, T, M]: bias over the feature dim only
    pre_act = helper.append_bias_op(pre_bias, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type):
    """Pool a sequence to one row per instance (reference nn.py
    sequence_pool; pool_type in sum/average/sqrt/max/last/first)."""
    helper = LayerHelper("sequence_pool", input=input)
    out = helper.create_tmp_variable(input.dtype)
    max_index = helper.create_tmp_variable("int32")
    helper.append_op(type="sequence_pool", inputs={"X": [input]},
                     outputs={"Out": [out], "MaxIndex": [max_index]},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def sequence_expand(x, y, name=None):
    """Expand x to match y's sequence lengths (reference nn.py
    sequence_expand)."""
    helper = LayerHelper("sequence_expand", input=x, name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sequence_expand",
                     inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", input=input)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"new_dim": new_dim})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", input=input, name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", input=input, name=name)
    out = helper.create_tmp_variable(helper.input_dtype())
    helper.append_op(type="sequence_concat", inputs={"X": input},
                     outputs={"Out": [out]})
    return out


def sequence_erase(input, tokens, name=None):
    helper = LayerHelper("sequence_erase", input=input, name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_erase", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"tokens": list(tokens)})
    return out


def lod_reset(x, y=None, target_lod=None):
    """Reset sequence lengths (reference nn.py:3322 lod_reset)."""
    helper = LayerHelper("lod_reset", input=x)
    out = helper.create_tmp_variable(x.dtype)
    if y is not None:
        helper.append_op(type="lod_reset", inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]})
    elif target_lod is not None:
        helper.append_op(type="lod_reset", inputs={"X": [x]},
                         outputs={"Out": [out]},
                         attrs={"target_lod": list(target_lod)})
    else:
        raise ValueError("lod_reset needs y or target_lod")
    return out


def sequence_unfold(x):
    """Flatten a nested (lod_level=2) sequence batch [B, S, T, ...] into its
    sub-sequences [B*S, T, ...] so inner-level sequence ops apply (TPU-native
    nested-LoD idiom; reference nested offsets lod_tensor.h:55)."""
    helper = LayerHelper("sequence_unfold")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sequence_unfold", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_fold(x, outer_like):
    """Regroup flattened sub-sequences back to [B, S, ...] using the outer
    structure of `outer_like` (the var sequence_unfold was applied to)."""
    helper = LayerHelper("sequence_fold")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sequence_fold",
                     inputs={"X": [x], "OuterLike": [outer_like]},
                     outputs={"Out": [out]}, attrs={})
    return out


def sequence_mask(x, name=None):
    """[B, T] float mask of valid positions for a padded sequence var
    (1 inside each sequence, 0 in padding). Reads the lengths channel the
    feed path attaches to LoD feeds; full-length for dense feeds."""
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_tmp_variable("float32")
    helper.append_op(type="sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]})
    return out


def context_project(x, context_length, context_start=None, name=None):
    """Concatenate a window of neighboring timesteps onto the feature
    axis, zero-padded at sequence boundaries (reference gserver
    ContextProjection; the centered default matches
    trainer_config_helpers context_projection: start = -(L-1)//2)."""
    if context_start is None:
        context_start = -(context_length - 1) // 2
    helper = LayerHelper("context_project", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="context_project", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"context_length": context_length,
                            "context_start": context_start})
    return out
