"""Neural-network layers DSL (reference: python/paddle/fluid/layers/nn.py —
fc :81, embedding :188, conv2d :1120, pool2d :1425, batch_norm :1478,
layer_norm :1567, dropout :846, cross_entropy :892, reduces :2055-2239,
matmul :2428, softmax_with_cross_entropy :3135, one_hot :3254 …)."""

from __future__ import annotations

import math

import numpy as np

from ..framework.framework import Variable
from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "fc", "embedding", "dropout", "cross_entropy", "square_error_cost",
    "conv2d", "conv3d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "softmax_with_cross_entropy", "accuracy",
    "auc", "mean", "mul", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "matmul", "transpose", "reverse", "reshape", "split",
    "topk",
    "one_hot", "lrn", "l2_normalize", "clip", "clip_by_norm", "scale",
    "cast", "dropout", "autoincreased_step_counter", "smooth_l1", "log_loss",
    "label_smooth", "cos_sim", "expand", "squeeze", "unsqueeze", "gather",
    "scatter", "pad", "nce", "row_conv", "im2sequence", "multiplex",
    "sigmoid_cross_entropy_with_logits", "maxout",
    "linear_chain_crf", "crf_decoding", "beam_search", "beam_search_decode",
    "warpctc", "ctc_greedy_decoder", "ctc_align", "edit_distance", "chunk_eval",
    "precision_recall", "positive_negative_pair", "pool3d", "roi_pool",
    "prelu", "crop", "spp", "unpool", "conv3d_transpose",
    "max_pool2d_with_index", "conv_shift", "l1_norm",
    "fused_attention", "sparse_moe",

    "hsigmoid", "bilinear_interp", "selective_fc",
]


def _simple(op_type, x, attrs=None, extra_inputs=None, out_dtype=None,
            name=None, outs=("Out",), in_slot="X"):
    helper = LayerHelper(op_type, name=name)
    inputs = {in_slot: [x]}
    if extra_inputs:
        inputs.update({k: v if isinstance(v, list) else [v]
                       for k, v in extra_inputs.items() if v is not None})
    outvars = [helper.create_tmp_variable(dtype=out_dtype or x.dtype)
               for _ in outs]
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={s: [v] for s, v in zip(outs, outvars)},
                     attrs=attrs or {})
    return outvars[0] if len(outvars) == 1 else tuple(outvars)


# --- fully connected --------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       use_mkldnn=False, act=None, is_test=False, name=None):
    """Fully connected layer (reference nn.py:81): out = act(sum_i X_i W_i + b).
    Lowers to `mul` (MXU matmul) + broadcast add; XLA fuses bias+activation."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, p_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_shape = [
            int(np.prod(input_shape[num_flatten_dims:]))
        ] + [size]
        w = helper.create_parameter(attr=p_attr, shape=param_shape,
                                    dtype=dtype, is_bias=False)
        tmp = helper.create_tmp_variable(dtype)
        helper.append_op(type="mul",
                         inputs={"X": [input_var], "Y": [w]},
                         outputs={"Out": [tmp]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32",
              shard_axis=None, cache_rows=None):
    """Embedding lookup (reference nn.py:188).

    is_sparse=True keeps the gradient a SelectedRows value end-to-end:
    lookup_table_grad emits (rows, values) and the sgd/momentum/adam
    scatter-apply kernels (ops/sparse_ops.py) update only the touched
    rows — the table never materializes a dense gradient.

    is_distributed (the reference's pserver-sharded table) maps to
    row-sharding the table over the program's mesh: the table partitions
    over `shard_axis` (default PADDLE_TPU_EMB_SHARD_AXIS, "fsdp") and
    lookups mod-shard-route ids under pd.coll.emb_lookup. Pass
    shard_axis explicitly (an axis name or tuple) to shard without the
    is_distributed flag.

    cache_rows=N opts the table into the beyond-HBM hot-row cache
    (parallel/emb_cache.py): only N rows live on device, the full table
    stays authoritative in host DRAM, and ids remap to cache slots at
    feed time. The request is recorded here; emb_cache.enable(program)
    activates it after the startup program ran (requires is_sparse=True
    and is mutually exclusive with sharding/padding_idx)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(attr=helper.param_attr, shape=size,
                                dtype=dtype, is_bias=False)
    tmp = helper.create_tmp_variable(dtype)
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(type="lookup_table",
                     inputs={"W": [w], "Ids": [input]},
                     outputs={"Out": [tmp]},
                     attrs={"is_sparse": is_sparse, "padding_idx": padding_idx})
    if shard_axis is not None or is_distributed:
        from ..parallel import embedding as embedding_mod
        embedding_mod.shard_table(helper.main_program, w.name, shard_axis)
    if cache_rows is not None:
        from ..parallel import emb_cache as emb_cache_mod
        emb_cache_mod.request_cache(helper.main_program, w.name,
                                    cache_rows)
    return tmp


# --- losses -----------------------------------------------------------------

def cross_entropy(input, label, soft_label=False):
    return _simple("cross_entropy", input,
                   attrs={"soft_label": soft_label},
                   extra_inputs={"Label": label}, outs=("Y",))


def square_error_cost(input, label):
    """(input - label)^2, built from elementwise ops (reference nn.py:965)."""
    helper = LayerHelper("square_error_cost", input=input)
    minus_out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="elementwise_sub",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [minus_out]})
    square_out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="square", inputs={"X": [minus_out]},
                     outputs={"Out": [square_out]})
    return square_out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               seq_mask=False):
    """Padded-sequence logits are masked automatically via the SEQLEN side
    channel. seq_mask=True additionally asserts the logits ARE a sequence
    (lod/rank-3), catching silent no-mask situations at build time."""
    if seq_mask:
        assert logits.shape is not None and len(logits.shape) >= 3, (
            "seq_mask=True but logits are not sequence-shaped [B,T,V]; "
            "feed the sequence through LoD data vars so lengths ride along")
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_tmp_variable(dtype=logits.dtype)
    loss = helper.create_tmp_variable(dtype=logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [softmax], "Loss": [loss]},
                     attrs={"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label):
    return _simple("sigmoid_cross_entropy_with_logits", x,
                   extra_inputs={"Label": label})


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_tmp_variable(dtype=x.dtype)
    loss = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="smooth_l1_loss",
                     inputs={"X": [x], "Y": [y],
                             **({"InsideWeight": [inside_weight]}
                                if inside_weight is not None else {}),
                             **({"OutsideWeight": [outside_weight]}
                                if outside_weight is not None else {})},
                     outputs={"Diff": [diff], "Out": [loss]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [loss]}, attrs={"epsilon": epsilon})
    return loss


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_tmp_variable(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": float(epsilon)})
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None):
    """Noise-contrastive estimation loss (reference nce_op.cc, nn.py:2806)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    dim = input.shape[1]
    num_neg_samples = 10 if num_neg_samples is None else num_neg_samples
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype, is_bias=False)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[num_total_classes, 1],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_tmp_variable(dtype=input.dtype)
    sample_logits = helper.create_tmp_variable(dtype=input.dtype)
    sample_labels = helper.create_tmp_variable(dtype="int64")
    inputs = {"Input": [input], "Label": [label], "Weight": [w], "Bias": [b]}
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    helper.append_op(type="nce", inputs=inputs,
                     outputs={"Cost": [cost], "SampleLogits": [sample_logits],
                              "SampleLabels": [sample_labels]},
                     attrs={"num_total_classes": num_total_classes,
                            "num_neg_samples": num_neg_samples})
    return cost


# --- conv / pool ------------------------------------------------------------

def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None):
    """2-D convolution, NCHW (reference nn.py:1120). use_cudnn is accepted and
    ignored: XLA picks the MXU convolution algorithm."""
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1]
    filter_size = _pair(filter_size)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = math.sqrt(2.0 / fan_in)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, std))
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="conv2d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation), "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1]
    fs = _triple(filter_size)
    filter_shape = [num_filters, num_channels // groups] + fs
    fan_in = (num_channels // groups) * fs[0] * fs[1] * fs[2]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, math.sqrt(2.0 / fan_in)))
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="conv3d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": _triple(stride),
                            "paddings": _triple(padding),
                            "dilations": _triple(dilation), "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    padding_, stride_, dilation_ = _pair(padding), _pair(stride), _pair(dilation)
    if filter_size is None:
        assert output_size is not None
        output_size = _pair(output_size)
        h, w_ = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h - 1) * stride_[0] + 2 * padding_[0] - 1)
            // dilation_[0] + 1,
            (output_size[1] - (w_ - 1) * stride_[1] + 2 * padding_[1] - 1)
            // dilation_[1] + 1]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=dtype, is_bias=False)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="conv2d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": stride_, "paddings": padding_,
                            "dilations": dilation_})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, use_mkldnn=False, name=None):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type,
                            "ksize": _pair(pool_size),
                            "global_pooling": global_pooling,
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding),
                            "ceil_mode": ceil_mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"groups": groups})
    return out


# --- normalization ----------------------------------------------------------

def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, use_mkldnn=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=False):
    """Batch normalization (reference nn.py:1478, batch_norm_op.cc)."""
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    input_shape = input.shape
    channel_num = input_shape[1] if data_layout == "NCHW" else input_shape[-1]
    param_shape = [channel_num]
    scale = helper.create_parameter(
        attr=helper.param_attr, shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(attr=helper.bias_attr, shape=param_shape,
                                   dtype=dtype, is_bias=True)
    mean = helper.create_parameter(
        attr=_non_trainable_attr(moving_mean_name),
        shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(0.0))
    variance = helper.create_parameter(
        attr=_non_trainable_attr(moving_variance_name),
        shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    saved_mean = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    saved_variance = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    out = input if in_place else helper.create_tmp_variable(dtype)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_variance]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test})
    return helper.append_activation(out)


def _non_trainable_attr(name):
    from ..param_attr import ParamAttr
    return ParamAttr(name=name, trainable=False)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr, shape=param_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(attr=helper.bias_attr, shape=param_shape,
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean_out = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    variance_out = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean_out],
                              "Variance": [variance_out]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    mid = helper.create_tmp_variable(dtype=input.dtype, stop_gradient=True)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    norm = helper.create_tmp_variable(dtype=x.dtype, stop_gradient=True)
    helper.append_op(type="norm", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


# --- dropout ----------------------------------------------------------------

def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    mask = helper.create_tmp_variable(dtype=x.dtype, stop_gradient=True)
    helper.append_op(type="dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "seed": seed if seed is not None else 0})
    return out


# --- metrics ----------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_tmp_variable(dtype=input.dtype)
    topk_indices = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [topk_out], "Indices": [topk_indices]},
                     attrs={"k": k})
    acc_out = helper.create_tmp_variable(dtype="float32")
    correct = correct or helper.create_tmp_variable(dtype="int32")
    total = total or helper.create_tmp_variable(dtype="int32")
    helper.append_op(type="accuracy",
                     inputs={"Out": [topk_out], "Indices": [topk_indices],
                             "Label": [label]},
                     outputs={"Accuracy": [acc_out], "Correct": [correct],
                              "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1):
    helper = LayerHelper("auc")
    auc_out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(type="auc",
                     inputs={"Out": [input], "Label": [label]},
                     outputs={"AUC": [auc_out]},
                     attrs={"curve": curve, "num_thresholds": num_thresholds})
    return auc_out


# --- math wrappers ----------------------------------------------------------

def mean(x, name=None):
    return _simple("mean", x, name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _simple("mul", x, attrs={"x_num_col_dims": x_num_col_dims,
                                    "y_num_col_dims": y_num_col_dims},
                   extra_inputs={"Y": y}, name=name)


def _ew(op_type):
    def f(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_tmp_variable(dtype=x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out)
    f.__name__ = op_type
    return f


elementwise_add = _ew("elementwise_add")
elementwise_sub = _ew("elementwise_sub")
elementwise_mul = _ew("elementwise_mul")
elementwise_div = _ew("elementwise_div")
elementwise_max = _ew("elementwise_max")
elementwise_min = _ew("elementwise_min")
elementwise_pow = _ew("elementwise_pow")


def _reduce(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(dtype=input.dtype)
        if dim is not None and not isinstance(dim, (list, tuple)):
            dim = [dim]
        helper.append_op(type=op_type, inputs={"X": [input]},
                         outputs={"Out": [out]},
                         attrs={"dim": dim if dim is not None else [0],
                                "keep_dim": keep_dim,
                                "reduce_all": dim is None})
        return out
    f.__name__ = op_type
    return f


reduce_sum = _reduce("reduce_sum")
reduce_mean = _reduce("reduce_mean")
reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")
reduce_prod = _reduce("reduce_prod")


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _simple("matmul", x,
                   attrs={"transpose_X": transpose_x,
                          "transpose_Y": transpose_y},
                   extra_inputs={"Y": y}, name=name)


def transpose(x, perm, name=None):
    return _simple("transpose", x, attrs={"axis": list(perm)}, name=name)


def reverse(x, axis, name=None):
    """Flip x along `axis` (int or list of ints)."""
    axis = [axis] if isinstance(axis, int) else list(axis)
    return _simple("reverse", x, attrs={"axis": axis}, name=name)


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    helper = LayerHelper("reshape", act=act, name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"shape": list(shape)})
    return helper.append_activation(out)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    input_shape = input.shape
    dim = dim if dim >= 0 else dim + len(input_shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [helper.create_tmp_variable(dtype=input.dtype) for _ in range(num)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"axis": dim, "sections": sections, "num": 0 if sections else num})
    return outs


def topk(input, k):
    helper = LayerHelper("top_k")
    values = helper.create_tmp_variable(dtype=input.dtype)
    indices = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    return values, indices


def one_hot(input, depth):
    return _simple("one_hot", input, attrs={"depth": depth},
                   out_dtype="float32")


def clip(x, min, max, name=None):
    return _simple("clip", x, attrs={"min": float(min), "max": float(max)},
                   name=name)


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", x, attrs={"max_norm": float(max_norm)},
                   name=name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def cast(x, dtype):
    from .tensor import cast as _cast
    return _cast(x, dtype)


def expand(x, expand_times, name=None):
    return _simple("expand", x, attrs={"expand_times": list(expand_times)},
                   name=name)


def squeeze(input, axes, name=None):
    return _simple("squeeze", input, attrs={"axes": list(axes)}, name=name)


def unsqueeze(input, axes, name=None):
    return _simple("unsqueeze", input, attrs={"axes": list(axes)}, name=name)


def gather(input, index):
    return _simple("gather", input, extra_inputs={"Index": index})


def scatter(input, index, updates, name=None):
    return _simple("scatter", input,
                   extra_inputs={"Ids": index, "Updates": updates}, name=name)


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", x, attrs={"paddings": list(paddings),
                                    "pad_value": float(pad_value)}, name=name)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_tmp_variable(dtype=inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": inputs, "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_tmp_variable(dtype=X.dtype)
    xnorm = helper.create_tmp_variable(dtype=X.dtype, stop_gradient=True)
    ynorm = helper.create_tmp_variable(dtype=X.dtype, stop_gradient=True)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xnorm],
                              "YNorm": [ynorm]})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape,
                                dtype=input.dtype, is_bias=False)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": _pair(filter_size),
                            "strides": _pair(stride),
                            "paddings": _pair(padding) + _pair(padding)})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter as a graph var (reference nn.py:3291); LR schedules
    read it."""
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    gblock = helper.main_program.global_block()
    if gblock.has_var(counter_name):
        return gblock.var(counter_name)
    counter = gblock.create_var(name=counter_name, dtype="int64", shape=[1],
                                persistable=True)
    from ..initializer import ConstantInitializer
    helper.set_variable_initializer(
        counter, ConstantInitializer(float(begin - 1)))
    gblock.prepend_op(type="increment", inputs={"X": [counter]},
                      outputs={"Out": [counter]}, attrs={"step": float(step)})
    counter.stop_gradient = True
    counter.desc.stop_gradient = True
    return counter


# --- CRF --------------------------------------------------------------------

def linear_chain_crf(input, label, param_attr=None):
    """Linear-chain CRF cost (reference nn.py:787, linear_chain_crf_op.cc).
    input: padded emissions [B,T,D]; label: [B,T,1] int. Returns the per-
    sequence negative log-likelihood [B,1]. The transition parameter is
    [D+2, D] (start row, stop row, pairwise matrix)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size], dtype=input.dtype)
    log_likelihood = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="linear_chain_crf",
                     inputs={"Emission": [input],
                             "Transition": [transition],
                             "Label": [label]},
                     outputs={"LogLikelihood": [log_likelihood]})
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    """Viterbi decoding (reference crf_decoding_op.cc). With label, emits a
    per-token correctness indicator instead of the path."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    transition = helper.get_parameter(helper.param_attr.name)
    viterbi_path = helper.create_tmp_variable("int64")
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [viterbi_path]})
    return viterbi_path


# --- beam search ------------------------------------------------------------

def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, level=0):
    """One beam-search step over dense [B,K] lanes (reference nn.py:1903,
    beam_search_op.cc; the reference tracks beams in LoD levels — here
    parent indices are returned explicitly). Returns
    (selected_ids [B,K], selected_scores [B,K], parent_idx [B,K])."""
    helper = LayerHelper("beam_search")
    selected_ids = helper.create_tmp_variable("int64")
    selected_scores = helper.create_tmp_variable(scores.dtype)
    parent_idx = helper.create_tmp_variable("int32")
    helper.append_op(type="beam_search",
                     inputs={"pre_ids": [pre_ids],
                             "pre_scores": [pre_scores],
                             "scores": [scores]},
                     outputs={"selected_ids": [selected_ids],
                              "selected_scores": [selected_scores],
                              "parent_idx": [parent_idx]},
                     attrs={"beam_size": beam_size, "end_id": end_id,
                            "level": level})
    return selected_ids, selected_scores, parent_idx


def beam_search_decode(ids, parent_idx, scores=None, beam_size=None,
                       end_id=1):
    """Backtrack beam TensorArrays into final hypotheses (reference
    beam_search_decode_op.cc). Returns (sentence_ids [B,K,T],
    sentence_scores [B,K])."""
    helper = LayerHelper("beam_search_decode")
    sentence_ids = helper.create_tmp_variable("int64")
    sentence_scores = helper.create_tmp_variable("float32")
    inputs = {"Ids": [ids], "ParentIdx": [parent_idx]}
    if scores is not None:
        inputs["Scores"] = [scores]
    helper.append_op(type="beam_search_decode", inputs=inputs,
                     outputs={"SentenceIds": [sentence_ids],
                              "SentenceScores": [sentence_scores]},
                     attrs={"end_id": end_id})
    return sentence_ids, sentence_scores


# --- CTC / sequence metrics ---------------------------------------------------

def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss over padded-LoD logits (reference nn.py:2696, warpctc_op.cc;
    softmax applied internally). Returns [num_sequences, 1] loss."""
    helper = LayerHelper("warpctc")
    loss = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="warpctc",
                     inputs={"Logits": [input], "Label": [label]},
                     outputs={"Loss": [loss]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    """Greedy CTC decode: per-step argmax, then merge repeats + drop blanks
    (reference nn.py:2616: top_k -> ctc_align)."""
    helper = LayerHelper("ctc_greedy_decoder")
    _, idx = topk(input, k=1)
    out = helper.create_tmp_variable(idx.dtype)
    helper.append_op(type="ctc_align", inputs={"Input": [idx]},
                     outputs={"Output": [out]},
                     attrs={"blank": blank, "merge_repeated": True})
    return out


def ctc_align(input, blank=0, merge_repeated=True):
    """Raw ctc_align on an id sequence (reference ctc_align_op.h)."""
    helper = LayerHelper("ctc_align")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="ctc_align", inputs={"Input": [input]},
                     outputs={"Output": [out]},
                     attrs={"blank": blank, "merge_repeated": merge_repeated})
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  name=None):
    """Levenshtein distance per sequence pair (reference nn.py:2534,
    edit_distance_op.h). Returns (distance [B,1], sequence_num [1])."""
    helper = LayerHelper("edit_distance")
    if ignored_tokens is not None and len(ignored_tokens) > 0:
        erased_input = helper.create_tmp_variable(input.dtype)
        erased_label = helper.create_tmp_variable(label.dtype)
        helper.append_op(type="sequence_erase", inputs={"X": [input]},
                         outputs={"Out": [erased_input]},
                         attrs={"tokens": list(ignored_tokens)})
        helper.append_op(type="sequence_erase", inputs={"X": [label]},
                         outputs={"Out": [erased_label]},
                         attrs={"tokens": list(ignored_tokens)})
        input, label = erased_input, erased_label
    out = helper.create_tmp_variable("float32")
    seq_num = helper.create_tmp_variable("int32")
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": [input], "Refs": [label]},
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized})
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """Chunking precision/recall/F1 (reference nn.py:1015, chunk_eval_op.h).
    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks)."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_tmp_variable("float32")
    recall = helper.create_tmp_variable("float32")
    f1_score = helper.create_tmp_variable("float32")
    num_infer_chunks = helper.create_tmp_variable("int32")
    num_label_chunks = helper.create_tmp_variable("int32")
    num_correct_chunks = helper.create_tmp_variable("int32")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1_score],
                 "NumInferChunks": [num_infer_chunks],
                 "NumLabelChunks": [num_label_chunks],
                 "NumCorrectChunks": [num_correct_chunks]},
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": excluded_chunk_types or []})
    return (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
            num_correct_chunks)


def precision_recall(indices, labels, class_number, weights=None,
                     states_info=None):
    """Multi-class precision/recall metrics op wrapper (reference
    precision_recall_op.h). Returns (batch_metrics [6], accum_metrics [6],
    accum_states_info [C,4])."""
    helper = LayerHelper("precision_recall")
    batch_metrics = helper.create_tmp_variable("float32")
    accum_metrics = helper.create_tmp_variable("float32")
    accum_states = helper.create_tmp_variable("float32")
    inputs = {"Indices": [indices], "Labels": [labels]}
    if weights is not None:
        inputs["Weights"] = [weights]
    if states_info is not None:
        inputs["StatesInfo"] = [states_info]
    helper.append_op(type="precision_recall", inputs=inputs,
                     outputs={"BatchMetrics": [batch_metrics],
                              "AccumMetrics": [accum_metrics],
                              "AccumStatesInfo": [accum_states]},
                     attrs={"class_number": class_number})
    return batch_metrics, accum_metrics, accum_states


def positive_negative_pair(score, label, query_id, weight=None, column=-1):
    """Ranking pair counts per query (reference positive_negative_pair_op.h).
    Returns (positive_pair, negative_pair, neutral_pair)."""
    helper = LayerHelper("positive_negative_pair")
    pos, neg, neu = (helper.create_tmp_variable("float32") for _ in range(3))
    inputs = {"Score": [score], "Label": [label], "QueryID": [query_id]}
    if weight is not None:
        inputs["Weight"] = [weight]
    helper.append_op(type="positive_negative_pair", inputs=inputs,
                     outputs={"PositivePair": [pos], "NegativePair": [neg],
                              "NeutralPair": [neu]},
                     attrs={"column": column})
    return pos, neg, neu


# --- vision layer wrappers ----------------------------------------------------

def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False, name=None):
    """NCDHW pooling (reference pool_op.cc pool3d)."""
    def _t(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v, v]
    helper = LayerHelper("pool3d")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type,
                            "ksize": _t(pool_size),
                            "strides": _t(pool_stride),
                            "paddings": _t(pool_padding),
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode})
    return out


def max_pool2d_with_index(input, pool_size, pool_stride=1, pool_padding=0):
    """Max pool returning (out, argmax-mask) (reference
    pool_with_index_op.cc)."""
    helper = LayerHelper("max_pool2d_with_index")
    out = helper.create_tmp_variable(input.dtype)
    mask = helper.create_tmp_variable("int32")
    helper.append_op(type="max_pool2d_with_index", inputs={"X": [input]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"ksize": _pair(pool_size),
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding)})
    return out, mask


def unpool(input, indices, unpooled_size):
    """Max unpooling from argmax indices (reference unpool_op.cc)."""
    helper = LayerHelper("unpool")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="unpool",
                     inputs={"X": [input], "Indices": [indices]},
                     outputs={"Out": [out]},
                     attrs={"unpooled_size": list(unpooled_size)})
    return out


def spp(input, pyramid_height, pool_type="max"):
    """Spatial pyramid pooling (reference spp_op.cc)."""
    helper = LayerHelper("spp")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="spp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": pyramid_height,
                            "pooling_type": pool_type})
    return out


def roi_pool(input, rois, pooled_height, pooled_width, spatial_scale=1.0,
             rois_batch_id=None):
    """ROI max pooling (reference roi_pool_op.cc)."""
    helper = LayerHelper("roi_pool")
    out = helper.create_tmp_variable(input.dtype)
    argmax = helper.create_tmp_variable("int32")
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoiBatchId"] = [rois_batch_id]
    helper.append_op(type="roi_pool", inputs=inputs,
                     outputs={"Out": [out], "Argmax": [argmax]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Crop x to shape at offsets (reference crop_op.cc); shape/offsets may
    be lists or Variables."""
    helper = LayerHelper("crop")
    out = helper.create_tmp_variable(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, Variable):
        inputs["Y"] = [shape]
    else:
        attrs["shape"] = list(shape)
    if isinstance(offsets, Variable):
        inputs["Offsets"] = [offsets]
    elif offsets is not None:
        attrs["offsets"] = list(offsets)
    helper.append_op(type="crop", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    """Parametric ReLU with learned alpha (reference prelu_op.cc)."""
    helper = LayerHelper("prelu", param_attr=param_attr)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        # element mode: one alpha per feature element, broadcast over the
        # batch dim (which is -1 for data vars and must not size a param)
        alpha_shape = [1] + list(x.shape[1:])
    from ..initializer import Constant
    alpha = helper.create_parameter(attr=helper.param_attr,
                                    shape=alpha_shape, dtype=x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="prelu",
                     inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    """Transposed 3D convolution (reference conv_transpose_op.cc)."""
    def _t(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v, v]
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    cin = input.shape[1]
    stride_, padding_, dilation_ = _t(stride), _t(padding), _t(dilation)
    if filter_size is None:
        assert output_size is not None, \
            "conv3d_transpose needs filter_size or output_size"
        output_size = _t(output_size)
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride_[i]
             + 2 * padding_[i] - 1) // dilation_[i] + 1
            for i in range(3)]
    else:
        filter_size = _t(filter_size)
    f = helper.create_parameter(
        attr=helper.param_attr,
        shape=[cin, num_filters] + filter_size, dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="conv3d_transpose",
                     inputs={"Input": [input], "Filter": [f]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride_, "paddings": padding_,
                            "dilations": dilation_})
    out = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(out)


def conv_shift(x, y, name=None):
    """Circular convolution (reference conv_shift_op.cc)."""
    helper = LayerHelper("conv_shift")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="conv_shift", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={})
    return out


def l1_norm(x, name=None):
    """Sum of absolute values (reference l1_norm_op.cc)."""
    helper = LayerHelper("l1_norm")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="l1_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={})
    return out


def fused_attention(q, k, v, causal=False,
                    sequence_parallel=False, use_flash="auto", name=None):
    """Fused attention over [B, T, H, D] tensors; sequence_parallel=True
    runs ring attention over the program mesh's 'sp' axis
    (parallel/ring_attention.py) for long-context training; use_flash=True
    runs the Pallas online-softmax VMEM kernel (ops/pallas_attention.py) —
    O(T) memory, scores never hit HBM. The default 'auto' picks per shape:
    XLA einsum at short T (fuses into neighbors), flash at long T (env
    PADDLE_TPU_FLASH_AUTO_T, ops/nn_ops._flash_auto_threshold); False
    forces einsum. (Named fused_attention because reference-parity
    nets.scaled_dot_product_attention already takes [B, T, D] with
    num_heads and different semantics.)"""
    helper = LayerHelper("fused_attention")
    out = helper.create_tmp_variable(q.dtype)
    # per-row logsumexp residual for the explicit backward (dropout-Mask
    # pattern); stop_gradient — it carries no cotangent of its own
    lse = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(type="scaled_dot_product_attention",
                     inputs={"Q": [q], "K": [k], "V": [v]},
                     outputs={"Out": [out], "LSE": [lse]},
                     attrs={"causal": causal,
                            "sequence_parallel": sequence_parallel,
                            "use_flash": use_flash})
    return out


def sparse_moe(x, num_experts, hidden_size, capacity_factor=1.25,
               param_attr=None, name=None):
    """Top-1 gated mixture-of-experts FFN over [N, D] tokens (GShard-style
    dispatch; see ops/nn_ops.py moe_ffn). Shard the returned layer's W1/W2
    over an 'ep' mesh axis with parallel.shard_parameter for expert
    parallelism."""
    helper = LayerHelper("sparse_moe", param_attr=param_attr)
    d = x.shape[-1]
    # one ParamAttr instance per parameter: create_parameter binds the
    # attr's name, so sharing one attr across gate/W1/W2 would collide
    import copy as _copy

    def _attr(suffix):
        a = helper.param_attr
        a = _copy.deepcopy(a)
        if getattr(a, "name", None):
            a.name = f"{a.name}.{suffix}"
        return a

    gate_w = helper.create_parameter(attr=_attr("gate"),
                                     shape=[d, num_experts], dtype=x.dtype)
    w1 = helper.create_parameter(attr=_attr("w1"),
                                 shape=[num_experts, d, hidden_size],
                                 dtype=x.dtype)
    w2 = helper.create_parameter(attr=_attr("w2"),
                                 shape=[num_experts, hidden_size, d],
                                 dtype=x.dtype)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="moe_ffn",
                     inputs={"X": [x], "GateW": [gate_w],
                             "W1": [w1], "W2": [w2]},
                     outputs={"Out": [out]},
                     attrs={"capacity_factor": capacity_factor})
    return out


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid classification cost over a complete binary tree
    (reference gserver HierarchicalSigmoidLayer.cpp; fluid hsigmoid). Cost
    is -log P(label) under the tree factorization; O(log C) tree nodes per
    sample instead of a C-way softmax. Returns [B, 1]."""
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[1, num_classes - 1],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    cost = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="hierarchical_sigmoid", inputs=inputs,
                     outputs={"Cost": [cost]},
                     attrs={"num_classes": int(num_classes)})
    return cost


def bilinear_interp(input, out_h, out_w, name=None):
    """Bilinear upsampling of NCHW feature maps (reference gserver
    BilinearInterpLayer.cpp; corners-aligned ratio (in-1)/(out-1))."""
    helper = LayerHelper("bilinear_interp", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="bilinear_interp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"out_h": int(out_h), "out_w": int(out_w)})
    return out


def selective_fc(input, select, size, act=None, param_attr=None,
                 bias_attr=None, name=None):
    """Fully-connected layer computing only selected output columns per
    sample (reference gserver SelectiveFullyConnectedLayer.cpp: with a
    selection the layer evaluates just those columns; the TPU-native dense
    form computes the full gemm on the MXU and masks — identical outputs,
    zeros at unselected columns, and XLA fuses the mask into the gemm
    epilogue). `select` is a [B, size] 0/1 mask."""
    out = fc(input=input, size=size, act=act, param_attr=param_attr,
             bias_attr=bias_attr, name=name)
    helper = LayerHelper("selective_fc", name=name)
    masked = helper.create_tmp_variable(out.dtype)
    helper.append_op(type="elementwise_mul",
                     inputs={"X": [out], "Y": [select]},
                     outputs={"Out": [masked]}, attrs={"axis": -1})
    return masked
