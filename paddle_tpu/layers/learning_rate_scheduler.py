"""LR schedules built as graph ops on a global step counter
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py:43-180)."""

from __future__ import annotations

import math

from ..layer_helper import LayerHelper
from . import nn
from . import ops as _ops
from . import tensor

__all__ = ["exponential_decay", "natural_exp_decay", "inverse_time_decay",
           "polynomial_decay", "piecewise_decay", "noam_decay"]


def _global_step():
    return nn.autoincreased_step_counter()


def _float_step():
    return tensor.cast(_global_step(), "float32")


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _float_step()
    div = step / float(decay_steps)
    if staircase:
        div = _ops.floor(div)
    return learning_rate * (decay_rate ** div)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _float_step()
    div = step / float(decay_steps)
    if staircase:
        div = _ops.floor(div)
    return learning_rate * _ops.exp(-1.0 * decay_rate * div)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _float_step()
    div = step / float(decay_steps)
    if staircase:
        div = _ops.floor(div)
    return learning_rate / (1.0 + decay_rate * div)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _float_step()
    if cycle:
        div = _ops.ceil(step / float(decay_steps))
        div = nn.elementwise_max(
            div, tensor.fill_constant([1], "float32", 1.0))
        decay_var = div * float(decay_steps)
        frac = step / decay_var
    else:
        frac = nn.elementwise_min(
            step / float(decay_steps),
            tensor.fill_constant([1], "float32", 1.0))
    return (learning_rate - end_learning_rate) * \
        ((1.0 - frac) ** power) + end_learning_rate


def piecewise_decay(boundaries, values):
    """Piecewise-constant LR: values[i] while step < boundaries[i]."""
    assert len(values) - len(boundaries) == 1
    step = _float_step()
    lr = tensor.fill_constant([1], "float32", values[-1])
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        below = tensor.cast(step < tensor.fill_constant([1], "float32",
                                                        float(b)), "float32")
        lr = below * v + (1.0 - below) * lr
    return lr


def noam_decay(d_model, warmup_steps):
    step = _float_step()
    a = step ** -0.5
    b = (warmup_steps ** -1.5) * step
    return (d_model ** -0.5) * nn.elementwise_min(a, b)
