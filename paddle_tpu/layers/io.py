"""Data-input layers (reference: python/paddle/fluid/layers/io.py:29 data)."""

from __future__ import annotations

from ..framework.desc import VarType
from ..framework.framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarType.LOD_TENSOR, stop_gradient=True):
    """Declare a feed variable (reference io.py:29). With append_batch_size,
    a -1 batch dim is prepended; the executor binds actual shapes at run."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if lod_level and lod_level > 0:
        # padded-LoD convention (executor.pack_to_padded): sequence feeds are
        # dense [batch, time, ...features], vs the reference's packed
        # [sum_len, ...features]; one -1 time dim per lod level
        shape = [-1] * lod_level + shape
    if append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(name=name, shape=shape, dtype=dtype,
                                  type=type, lod_level=lod_level,
                                  stop_gradient=stop_gradient)
    var.desc.stop_gradient = stop_gradient
    # mirror in startup program so save/load sees consistent descs
    return var
