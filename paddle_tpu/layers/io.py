"""Data-input layers: feed declarations and file readers
(reference: python/paddle/fluid/layers/io.py:29 data, :261 open_recordio_file,
:290 read_file, :334 shuffle, :347 double_buffer(batch)).

The reference builds readers as graph ops (ReaderHolder variables chained
through decorated-reader ops, framework/reader.h:28-68) executed by the
C++ executor. Here the reader chain is a host-side pipeline bound to the
program: `read_file` declares the data variables and registers the pipeline,
and `Executor.run` pulls the next batch from it when no explicit feed is
given — same user code shape (`while True: exe.run()` until EOF), with the
double-buffer stage doing the host->HBM prefetch overlap that the
reference's double_buffer reader op does."""

from __future__ import annotations

import numpy as np

from ..framework.desc import VarType
from ..framework.framework import default_main_program, default_startup_program

__all__ = ["data", "open_recordio_file", "read_file", "shuffle", "batch",
           "multi_pass", "double_buffer", "EOFException"]


class EOFException(Exception):
    """Raised by Executor.run when a program-bound reader is exhausted
    (reference: fluid.core.EOFException from reader ops)."""


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarType.LOD_TENSOR, stop_gradient=True):
    """Declare a feed variable (reference io.py:29). With append_batch_size,
    a -1 batch dim is prepended; the executor binds actual shapes at run."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if lod_level and lod_level > 0:
        # padded-LoD convention (executor.pack_to_padded): sequence feeds are
        # dense [batch, time, ...features], vs the reference's packed
        # [sum_len, ...features]; one -1 time dim per lod level
        shape = [-1] * lod_level + shape
    if append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(name=name, shape=shape, dtype=dtype,
                                  type=type, lod_level=lod_level,
                                  stop_gradient=stop_gradient)
    var.desc.stop_gradient = stop_gradient
    return var


class FileReader:
    """Host-side reader pipeline handle (the ReaderHolder equivalent,
    reference framework/reader.h:28). `source` yields per-sample tuples of
    arrays; decorators rebind `source`; `read_file` attaches the finished
    chain to the program."""

    def __init__(self, source, dtypes, shapes=None, lod_levels=None):
        self.source = source            # callable -> iterable of tuples
        self.dtypes = list(dtypes)
        self.shapes = list(shapes) if shapes else None
        self.lod_levels = list(lod_levels or [0] * len(self.dtypes))
        self.batched = False
        self.buffered = False           # double_buffer applied
        self._iter = None
        self._feeder = None

    def reset(self):
        # stop a live prefetch thread so it does not stay blocked on the
        # queue holding device-resident batches across passes
        if self._feeder is not None:
            self._feeder.stop()
            self._feeder = None
        self._iter = None

    def _start(self, device):
        if self._feeder is not None:
            self._feeder.stop()
            self._feeder = None
        it = self.source()
        if self.buffered:
            from ..reader.pipeline import DoubleBufferedFeeder
            import jax

            def to_feed(t):
                # the producer thread stages plain arrays in device memory
                # ahead of consumption (the double_buffer decorator's H2D
                # overlap); LoDTensors stay host-side — the executor must
                # pack them before upload
                if device is not None:
                    t = tuple(
                        jax.device_put(v, device)
                        if isinstance(v, (np.ndarray, np.generic)) else v
                        for v in t)
                return {"__tuple__": t}

            dbf = DoubleBufferedFeeder(
                lambda: self.source(), to_feed=to_feed, device=None)
            self._feeder = dbf
            it = (d["__tuple__"] for d in dbf)
        self._iter = iter(it)

    def next_batch(self, device=None):
        if self._iter is None:
            self._start(device)
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            raise EOFException("reader exhausted; call reader.reset()")


def open_recordio_file(filename, shapes, lod_levels, dtypes,
                       pass_num=1, for_parallel=False):
    """Reader over a RecordIO file of pickled sample tuples (reference
    io.py:261 + operators/reader/create_recordio_file_reader_op.cc)."""
    from .. import recordio as recordio_mod

    def source():
        for _ in range(max(pass_num, 1)):
            for sample in recordio_mod.read_samples(filename):
                yield tuple(np.asarray(a) for a in sample)

    return FileReader(source, dtypes, shapes, lod_levels)


def shuffle(reader, buffer_size):
    """Buffered shuffle decorator (reference io.py:334,
    create_shuffle_reader_op.cc)."""
    import random
    inner = reader.source

    def source():
        buf = []
        for s in inner():
            buf.append(s)
            if len(buf) >= buffer_size:
                random.shuffle(buf)
                while buf:
                    yield buf.pop()
        random.shuffle(buf)
        while buf:
            yield buf.pop()

    reader.source = source
    return reader


def batch(reader, batch_size):
    """Batch samples into stacked arrays / packed LoD rows (reference
    create_batch_reader_op.cc). Variable-length slots (lod_level>0) come
    out as LoDTensors in the padded-feed convention."""
    from ..executor import LoDTensor
    inner = reader.source
    lod_levels = reader.lod_levels

    def make_batch(samples):
        out = []
        for i in range(len(samples[0])):
            rows = [s[i] for s in samples]
            if lod_levels[i] and lod_levels[i] > 0:
                flat = np.concatenate(rows, axis=0)
                offs = [0]
                for r in rows:
                    offs.append(offs[-1] + len(r))
                out.append(LoDTensor(flat, [offs]))
            else:
                out.append(np.stack(rows))
        return tuple(out)

    def source():
        chunk = []
        for s in inner():
            chunk.append(s)
            if len(chunk) == batch_size:
                yield make_batch(chunk)
                chunk = []
        if chunk:
            yield make_batch(chunk)

    reader.source = source
    reader.batched = True
    return reader


def multi_pass(reader, pass_num):
    """Re-run the underlying source pass_num times before EOF (reference
    create_multi_pass_reader_op.cc, test_multi_pass_reader.py): training
    loops drain one reader for N epochs without resetting it."""
    inner = reader.source

    def source():
        for _ in range(pass_num):
            for s in inner():
                yield s

    reader.source = source
    return reader


def double_buffer(reader, place=None, name=None):
    """Prefetch decorator (reference io.py:347,
    create_double_buffer_reader_op.cc): a producer thread stages the next
    batch while the current one computes."""
    reader.buffered = True
    return reader


def read_file(reader):
    """Bind the reader chain to the program and declare its output data
    variables (reference io.py:290 read_file + ReaderHolder). Executor.run
    with no feed pulls batches from here."""
    assert reader.batched, "apply fluid.layers.batch(reader, N) before read_file"
    prog = default_main_program()
    block = prog.current_block()
    out_vars = []
    n = len(reader.dtypes)
    for i in range(n):
        shape = list(reader.shapes[i]) if reader.shapes else [-1]
        lod = reader.lod_levels[i]
        name = f"_reader_out_{len(getattr(prog, '_pipeline_readers', []))}_{i}"
        if lod and lod > 0:
            shape = [-1] * lod + [s for s in shape if s != -1]
        var = block.create_var(name=name, shape=shape,
                               dtype=reader.dtypes[i], lod_level=lod,
                               stop_gradient=True)
        var.desc.stop_gradient = True
        out_vars.append(var)
    if not hasattr(prog, "_pipeline_readers"):
        prog._pipeline_readers = []
    prog._pipeline_readers.append((reader, [v.name for v in out_vars]))
    return out_vars if len(out_vars) > 1 else out_vars[0]
