"""Operator overloading on Variable (reference: python/paddle/fluid/layers/
math_op_patch.py): a + b, a * 2.0, a - b … build elementwise/scale ops."""

from __future__ import annotations

from ..framework.framework import Variable
from ..layer_helper import LayerHelper


def _scalar_op(var, scale, bias):
    helper = LayerHelper("scale")
    out = helper.create_tmp_variable(dtype=var.dtype)
    helper.append_op(type="scale", inputs={"X": [var]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias)})
    return out


def _const_like(var, value):
    """Constant var broadcastable against `var`, tolerating -1 batch dims."""
    shape = list(var.shape or [1])
    if any(d is None or d < 0 for d in shape):
        from .tensor import fill_constant_batch_size_like
        shape = [1 if (d is None or d < 0) else d for d in shape]
        return fill_constant_batch_size_like(var, shape, var.dtype, value)
    from .tensor import fill_constant
    return fill_constant(shape, var.dtype, value)


def _binary(op_type, reverse=False):
    def impl(self, other):
        if isinstance(other, (int, float)):
            if op_type == "elementwise_add":
                return _scalar_op(self, 1.0, other)
            if op_type == "elementwise_sub":
                if reverse:
                    return _scalar_op(self, -1.0, other)
                return _scalar_op(self, 1.0, -other)
            if op_type == "elementwise_mul":
                return _scalar_op(self, other, 0.0)
            if op_type == "elementwise_div" and not reverse:
                return _scalar_op(self, 1.0 / other, 0.0)
            if op_type == "elementwise_pow" and not reverse:
                # x ** scalar -> pow op with factor attr
                helper = LayerHelper("pow")
                out = helper.create_tmp_variable(dtype=self.dtype)
                helper.append_op(type="pow", inputs={"X": [self]},
                                 outputs={"Out": [out]},
                                 attrs={"factor": float(other)})
                return out
            other = _const_like(self, other)
        helper = LayerHelper(op_type)
        out = helper.create_tmp_variable(dtype=self.dtype)
        x, y = (other, self) if reverse else (self, other)
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]})
        return out
    return impl


def _compare(op_type):
    def impl(self, other):
        helper = LayerHelper(op_type)
        out = helper.create_tmp_variable(dtype="bool")
        helper.append_op(type=op_type, inputs={"X": [self], "Y": [other]},
                         outputs={"Out": [out]})
        return out
    return impl


def monkey_patch_variable():
    Variable.__add__ = _binary("elementwise_add")
    Variable.__radd__ = _binary("elementwise_add", reverse=True)
    Variable.__sub__ = _binary("elementwise_sub")
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary("elementwise_mul")
    Variable.__rmul__ = _binary("elementwise_mul", reverse=True)
    Variable.__truediv__ = _binary("elementwise_div")
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__rpow__ = _binary("elementwise_pow", reverse=True)
    Variable.__lt__ = _compare("less_than")
    Variable.__le__ = _compare("less_equal")
    Variable.__gt__ = _compare("greater_than")
    Variable.__ge__ = _compare("greater_equal")
    Variable.__neg__ = lambda self: _scalar_op(self, -1.0, 0.0)


monkey_patch_variable()
