#!/usr/bin/env python
"""Long-context transformer training — the capability tier the 2018
reference lacks entirely. One flag each for the Pallas flash kernels
(O(T) attention memory: forward online-softmax + backward recomputed
from the saved logsumexp) and for ring sequence parallelism (shard the
sequence over an 'sp' mesh axis; K/V rotate over ICI via ppermute).

Run single-device flash:
    python examples/fluid/train_transformer_long_context.py
Run the ring over 8 virtual devices:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fluid/train_transformer_long_context.py --ring
"""

import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import models


def build_programs(use_ring=False, seqlen=512, vocab=1024):
    """Programs-only surface for `python -m paddle_tpu analyze --example
    transformer_long_context` and the analyzer tests."""
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        tok = fluid.layers.data(name="tok", shape=[-1, seqlen],
                                dtype="int64", append_batch_size=False)
        lab = fluid.layers.data(name="lab", shape=[-1, seqlen],
                                dtype="int64", append_batch_size=False)
        loss, logits = models.transformer_lm(
            tok, lab, vocab_size=vocab, d_model=128, n_head=2, n_layer=2,
            use_flash=not use_ring, sequence_parallel=use_ring,
            return_logits=True)
        fluid.optimizer.Adam(learning_rate=3e-4).minimize(
            loss, startup_program=startup)
    if use_ring:
        import jax
        from paddle_tpu.parallel import mesh as mesh_mod
        main_prog._mesh = mesh_mod.make_mesh((len(jax.devices()),), ("sp",))
    return {"main": main_prog, "startup": startup,
            "feeds": ["tok", "lab"], "fetches": [loss.name], "loss": loss,
            # serving surface: prune to the pre-softmax logits, feeding
            # tokens only (token-level latency scenario)
            "infer_feeds": ["tok"], "infer_fetches": [logits.name]}


def main(use_ring=False):
    seqlen, vocab = 512, 1024
    built = build_programs(use_ring=use_ring, seqlen=seqlen, vocab=vocab)
    main_prog, loss = built["main"], built["loss"]

    exe = fluid.Executor(fluid.CPUPlace() if use_ring
                         else fluid.TPUPlace(0))
    exe.run(built["startup"])

    rng = np.random.default_rng(0)
    seq = rng.integers(0, vocab, (2, seqlen + 1))
    feed = {"tok": seq[:, :-1].astype(np.int64),
            "lab": seq[:, 1:].astype(np.int64)}
    for step in range(10):
        out, = exe.run(main_prog, feed=feed, fetch_list=[loss])
        print(f"step {step}: loss {float(np.ravel(out)[0]):.4f}")


if __name__ == "__main__":
    main(use_ring="--ring" in sys.argv)
