#!/usr/bin/env python
"""Linear regression on UCI housing — the canonical first fluid program
(reference tests/book/test_fit_a_line.py user flow): layers DSL ->
optimizer.minimize -> Executor over feed/fetch, then save + reload the
inference model.

Run:  python examples/fluid/train_fit_a_line.py
(CPU by default; set no env to use the TPU when one is attached.)
"""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.dataset as dataset
import paddle_tpu.minibatch as minibatch
import paddle_tpu.reader as reader


def build_programs():
    """The example's programs without running anything — the surface
    `python -m paddle_tpu analyze --example fit_a_line` and the analyzer
    tests drive."""
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(
            loss, startup_program=startup)
    return {"main": main_prog, "startup": startup, "feeds": ["x", "y"],
            "fetches": [loss.name], "x": x, "y": y, "pred": pred,
            "loss": loss}


def main():
    built = build_programs()
    x, y, pred, loss = built["x"], built["y"], built["pred"], built["loss"]

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(built["startup"])

    batched = minibatch.batch(
        reader.shuffle(dataset.uci_housing.train(), buf_size=500),
        batch_size=32)
    feeder = fluid.DataFeeder(place=place, feed_list=[x, y])

    for pass_id in range(10):
        for data in batched():
            avg, = exe.run(built["main"], feed=feeder.feed(data),
                           fetch_list=[loss])
        print(f"pass {pass_id}: loss {float(np.ravel(avg)[0]):.4f}")

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "fit_a_line.model")
        fluid.io.save_inference_model(path, ["x"], [pred], exe,
                                      main_program=built["main"])
        prog, feeds, fetches = fluid.io.load_inference_model(path, exe)
        sample = np.asarray(next(iter(batched()))[0][0],
                            np.float32).reshape(1, 13)
        out, = exe.run(prog, feed={"x": sample}, fetch_list=fetches)
        print("reloaded model predicts", float(np.ravel(out)[0]))


if __name__ == "__main__":
    main()
