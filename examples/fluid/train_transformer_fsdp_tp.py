#!/usr/bin/env python
"""Billion-parameter-class FSDP+TP LM training via the named-axis
sharding planner (ISSUE 15).

Two phases:

1. **Headroom proof** — build the big LM (IR only, nothing compiles or
   allocates), fit a `memory.HeadroomModel` over its replicated
   footprint (params + grads + momentum, plus per-example activations)
   and show `max_batch(budget) == 0`: a replicated copy cannot fit even
   an empty batch on one device.  Then `planner.plan` the same program
   over the data x fsdp x tp mesh and show the per-shard state DOES fit
   — the planner's whole value proposition in two numbers.

2. **Training** — train a mesh-divisible smoke config planned over
   (data=2, fsdp=2, tp=2) on 8 devices, verify per-shard byte
   accounting against `memory.per_shard_param_bytes`, train the same
   config replicated (plain dp mesh) and check loss parity to
   tolerance, and assert the overlap pass bucketed every dp/fsdp
   gradient — zero `sharded_param` fallbacks (the exact gap the
   spec-group buckets closed).

Run over 8 virtual devices:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fluid/train_transformer_fsdp_tp.py

On a real slice, scale the TRAINED model up to the proof config:
    python examples/fluid/train_transformer_fsdp_tp.py --train-big \
        --mesh dp=2,fsdp=2,tp=2

`--mesh` (or PADDLE_TPU_MESH) names the axes; `--d-model/--n-layer/
--vocab` size the big config (defaults ~2B params, over the 16GiB
v5e-class budget replicated, comfortably under it per-shard).
"""

import argparse
import os
import sys

import numpy as np

# `python examples/fluid/train_transformer_fsdp_tp.py` puts this dir
# (not the repo root) on sys.path; make the example runnable anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu import memory, telemetry
from paddle_tpu.framework import unique_name
from paddle_tpu.models import transformer_lm
from paddle_tpu.parallel import planner


def build_programs(vocab=512, d_model=64, n_layer=2, seqlen=64,
                   n_head=4, lr=0.01):
    """Programs-only surface (same contract as the other examples)."""
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        tok = fluid.layers.data(name="tok", shape=[seqlen], dtype="int64")
        lab = fluid.layers.data(name="lab", shape=[seqlen], dtype="int64")
        loss = transformer_lm(tok, lab, vocab_size=vocab, d_model=d_model,
                              n_head=n_head, n_layer=n_layer)
        fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9).minimize(
            loss, startup_program=startup)
    return {"main": main_prog, "startup": startup,
            "feeds": ["tok", "lab"], "fetches": [loss.name], "loss": loss}


# Momentum training holds param + gradient + velocity per weight at peak
STATE_MULT = 3


def _activation_bytes_per_example(d_model, n_layer, seqlen, vocab):
    """Rough lower bound on live fp32 activations per example: the
    residual stream plus qkv/attn-out/ffn intermediates (~12 D-wide
    tensors per block) and the [T, V] logits.  A lower bound is all the
    proof needs — the replicated verdict below is already sealed by the
    batch-independent state term."""
    per_layer = 12 * seqlen * d_model * 4
    return n_layer * per_layer + seqlen * vocab * 4


def prove_replicated_oom(args, mesh, budget):
    """Phase 1: the static headroom proof on the big config."""
    with unique_name.guard():
        built = build_programs(vocab=args.vocab, d_model=args.d_model,
                               n_layer=args.n_layer, seqlen=args.seqlen,
                               n_head=args.n_head)
    big = built["main"]
    # the plan's byte model is static (shapes only) — nothing allocates;
    # total_bytes() is the replicated copy every device would hold
    plan = planner.plan(big, mesh)
    param_bytes = plan.total_bytes
    per_item = _activation_bytes_per_example(
        args.d_model, args.n_layer, args.seqlen, args.vocab)
    fixed = param_bytes * STATE_MULT
    hm = memory.HeadroomModel.fit(
        [(1, fixed + per_item), (9, fixed + 9 * per_item)])
    mb = hm.max_batch(budget)
    gib = 1 << 30
    print(f"big config: {param_bytes / 4 / 1e9:.2f}B params, "
          f"replicated state {fixed / gib:.1f} GiB "
          f"vs budget {budget / gib:.1f} GiB")
    print(f"HeadroomModel.max_batch(budget) = {mb} -> "
          f"{'cannot fit ANY batch replicated' if mb == 0 else 'fits?!'}")
    assert mb == 0, "replicated big config unexpectedly fits the budget"

    sharded_fixed = plan.per_shard_bytes * STATE_MULT
    hm_planned = memory.HeadroomModel.fit(
        [(1, sharded_fixed + per_item), (9, sharded_fixed + 9 * per_item)])
    mb_planned = hm_planned.max_batch(budget)
    print(f"planned over {dict(zip(mesh.axis_names, mesh.devices.shape))}: "
          f"per-shard state {sharded_fixed / gib:.1f} GiB, "
          f"max_batch(budget) = {mb_planned}")
    assert sharded_fixed < budget, "per-shard state still over budget"
    assert mb_planned and mb_planned > 0
    by_role = {r: len(ps) for r, ps in plan.by_role().items()}
    print(f"roles: {by_role}")
    return built


def train(cfg, mesh=None, dp_mesh_devices=None, steps=5, batch=8):
    """Train `cfg` for `steps`; planned over `mesh` when given, else
    replicated (optionally SPMD over a plain dp mesh so the global batch
    math matches)."""
    with unique_name.guard():
        built = build_programs(**cfg)
    main_prog, loss = built["main"], built["loss"]
    if mesh is not None:
        plan = planner.plan(main_prog, mesh)
    elif dp_mesh_devices is not None:
        from paddle_tpu.parallel.mesh import make_mesh
        main_prog._mesh = make_mesh((len(dp_mesh_devices),), ("dp",),
                                    dp_mesh_devices)
    exe = fluid.Executor(fluid.TPUPlace(0))
    rng = np.random.default_rng(3)
    losses = []
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(built["startup"])
        if mesh is not None:
            checked = planner.validate_plan_bytes(main_prog, scope)
            print(f"byte accounting validated for {len(checked)} params")
        for step in range(steps):
            feed = {"tok": rng.integers(0, cfg["vocab"], (batch,
                    cfg["seqlen"]), dtype=np.int64),
                    "lab": rng.integers(0, cfg["vocab"], (batch,
                    cfg["seqlen"]), dtype=np.int64)}
            out, = exe.run(main_prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.ravel(np.asarray(out))[0]))
            print(f"  step {step}: loss {losses[-1]:.4f}")
    return losses


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mesh", default=os.environ.get(
        "PADDLE_TPU_MESH", "dp=2,fsdp=2,tp=2"),
        help="named mesh, e.g. dp=2,fsdp=2,tp=2")
    ap.add_argument("--d-model", type=int, default=2560)
    ap.add_argument("--n-layer", type=int, default=24)
    ap.add_argument("--n-head", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--seqlen", type=int, default=1024)
    ap.add_argument("--budget-gb", type=float, default=None,
                    help="per-device HBM budget for the headroom proof "
                         "(default: memory.default_budget)")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--train-big", action="store_true",
                    help="train the big config itself (real slices only)")
    args = ap.parse_args(argv)

    import jax
    mesh = planner.mesh_from_env(default=args.mesh)
    ndev = mesh.devices.size
    if len(jax.devices()) < ndev:
        raise SystemExit(
            f"mesh {args.mesh} needs {ndev} devices, have "
            f"{len(jax.devices())}; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={ndev}")
    budget = (int(args.budget_gb * (1 << 30)) if args.budget_gb
              else memory.default_budget())

    print("== phase 1: headroom proof (static, nothing allocates) ==")
    prove_replicated_oom(args, mesh, budget)

    print("== phase 2: planned training on the mesh ==")
    if args.train_big:
        cfg = dict(vocab=args.vocab, d_model=args.d_model,
                   n_layer=args.n_layer, seqlen=args.seqlen,
                   n_head=args.n_head)
    else:
        cfg = dict(vocab=512, d_model=64, n_layer=2, seqlen=64, n_head=4)
    telemetry.reset()
    planned = train(cfg, mesh=mesh, steps=args.steps)

    fallbacks = telemetry.read_series("overlap_fallback_total")
    sharded_param = sum(v for k, v in fallbacks.items()
                        if "reason=sharded_param" in k)
    buckets = sum(telemetry.read_series("overlap_buckets_total").values())
    print(f"overlap: {buckets} gradient buckets, "
          f"{sharded_param} sharded_param fallbacks")
    assert sharded_param == 0, \
        "dp/fsdp gradients fell back to the unscheduled sync"

    if not args.train_big:
        print("== phase 3: replicated baseline, loss parity ==")
        baseline = train(cfg, dp_mesh_devices=jax.devices()[:ndev],
                         steps=args.steps)
        np.testing.assert_allclose(planned, baseline, rtol=2e-4, atol=2e-5)
        print(f"parity ok: planned {planned[-1]:.4f} vs "
              f"replicated {baseline[-1]:.4f} at step {len(planned) - 1}")
    print("done")


if __name__ == "__main__":
    main(sys.argv[1:])
