#!/usr/bin/env python
"""Criteo-style DLRM on synthetic click logs — the recommender workload
the sharded sparse embedding path (ISSUE 10) exists for: 26 categorical
slots hash into one [rows, dim] table, a small dense MLP scores the
concatenated embeddings, and Adam trains the table end-to-end through
SelectedRows gradients and scatter-apply (the update is O(rows touched),
never O(table rows)).

Run:  python examples/fluid/train_criteo_dlrm.py              # replicated
      python examples/fluid/train_criteo_dlrm.py --sharded    # fsdp table
      python examples/fluid/train_criteo_dlrm.py --cache-budget-mb 8
                                                 # beyond-HBM hot-row cache

--sharded row-partitions the table (and its Adam moments) over an `fsdp`
mesh of every visible device, so per-device HBM for the table is
total/n_devices; on a CPU host export
XLA_FLAGS=--xla_force_host_platform_device_count=8 to see the 8-way
split. --rows/--dim/--slots rescale the table (the defaults keep the demo
laptop-sized; criteo-production would be --rows 1000000 and up — the
geometry the per-shard report is for).

--cache-budget-mb (ISSUE 14, mutually exclusive with --sharded) keeps
the authoritative table in host DRAM and only a budget-sized hot-row
slab on the device. The zipf click-log makes this the motivated case: a
small hot set covers most lookups, so the steady-state hit rate should
sit near the analytic zipf coverage of the cache. The script prints
that analytic floor at startup and EXITS NONZERO if the measured
steady-state hit rate lands below it — a regression gate on the
eviction policy, not just a demo.
"""

import argparse
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import telemetry
from paddle_tpu.parallel import embedding as emb_mod
from paddle_tpu.parallel.mesh import make_mesh


def build(rows, dim, slots):
    ids = fluid.layers.data(name="ids", shape=[slots], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    # one shared table for all slots (hash-trick style); per-slot tables
    # would just be 26 shard_table calls instead of one
    emb = fluid.layers.embedding(
        ids, size=[rows, dim], is_sparse=True,
        param_attr=fluid.ParamAttr(name="emb_table"))
    flat = fluid.layers.reshape(emb, shape=[-1, slots * dim])
    h = fluid.layers.fc(input=flat, size=256, act="relu")
    h = fluid.layers.fc(input=h, size=64, act="relu")
    logits = fluid.layers.fc(input=h, size=2)
    # prob is the serving fetch: pruning to it drops label/loss/backward
    # while keeping the sparse lookup -> MLP forward chain
    prob = fluid.layers.softmax(logits)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss, prob


def build_programs(rows=100000, dim=64, slots=26):
    """Programs-only surface for `python -m paddle_tpu analyze --example
    criteo_dlrm` and the analyzer tests: same graph as main(), built into
    fresh programs instead of the defaults."""
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        loss, prob = build(rows, dim, slots)
    return {"main": main_prog, "startup": startup,
            "feeds": ["ids", "label"], "fetches": [loss.name, prob.name],
            "loss": loss,
            "infer_feeds": ["ids"], "infer_fetches": [prob.name]}


ZIPF_SKEW = 1.3


def synthetic_clicks(rng, batch, rows, slots):
    """Zipf-ish id draws — recommender tables are hit head-heavy, which is
    exactly when scatter-apply (O(rows touched)) beats a dense update."""
    ids = np.minimum(rng.zipf(ZIPF_SKEW, size=(batch, slots)) - 1,
                     rows - 1).astype(np.int64)
    label = rng.integers(0, 2, (batch, 1)).astype(np.int64)
    return ids, label


def zipf_hit_rate_floor(cache_rows, rows, skew=ZIPF_SKEW):
    """Conservative analytic lower bound on the steady-state hit rate of
    a `cache_rows`-slot LRU cache under zipf(`skew`) draws over `rows`
    ids: the probability mass of the top cache_rows/2 ranks,
    H(cache_rows/2) / H(rows) with H(n) the partial harmonic sum
    sum_{r<=n} r^-skew. Deliberately slack twice over — an LRU's
    steady-state residency tracks the top-k set closely under this much
    skew (Che approximation), and the id clip in synthetic_clicks moves
    the over-`rows` tail mass onto one permanently-resident row — so a
    measured rate BELOW this bound means the eviction policy broke, not
    that the workload got unlucky."""
    k = max(1, min(int(cache_rows) // 2, int(rows)))
    r = np.arange(1, int(rows) + 1, dtype=np.float64)
    weights = r ** -float(skew)
    return float(weights[:k].sum() / weights.sum())


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--sharded", action="store_true",
                   help="fsdp-partition the table over all devices")
    p.add_argument("--rows", type=int, default=100000)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--slots", type=int, default=26)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--cache-budget-mb", type=float, default=None,
                   help="device bytes for the beyond-HBM hot-row cache; "
                        "the full table stays in host DRAM")
    args = p.parse_args(argv)
    if args.sharded and args.cache_budget_mb is not None:
        p.error("--sharded and --cache-budget-mb are mutually exclusive "
                "(both are beyond-HBM strategies for the same table)")

    loss, _prob = build(args.rows, args.dim, args.slots)
    main_prog = fluid.default_main_program()
    if args.sharded:
        import jax
        devs = jax.devices()
        main_prog._mesh = make_mesh((len(devs),), ("fsdp",))
        emb_mod.shard_table(main_prog, "emb_table", "fsdp")
        print(f"table [{args.rows}, {args.dim}] sharded over "
              f"{len(devs)} devices (axis 'fsdp')")

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    cache = floor = None
    if args.cache_budget_mb is not None:
        from paddle_tpu.parallel import emb_cache as emb_cache_mod
        cache = emb_cache_mod.enable(
            main_prog,
            budget_bytes=int(args.cache_budget_mb * (1 << 20)))
        if cache is None:
            print(f"--cache-budget-mb {args.cache_budget_mb} covers the "
                  f"whole [{args.rows}, {args.dim}] table — nothing to "
                  f"cache (or PADDLE_TPU_EMB_CACHE=0)")
        else:
            t = cache.tables()["emb_table"]
            floor = zipf_hit_rate_floor(t.cache_rows, args.rows)
            print(f"hot-row cache: {t.cache_rows} of {args.rows} rows "
                  f"device-resident ({args.cache_budget_mb} MB over "
                  f"{len(t.state_names)} slabs); analytic zipf"
                  f"({ZIPF_SKEW}) steady-state hit-rate floor "
                  f"{floor:.3f}")

    rng = np.random.default_rng(0)
    steady_base = None
    for step in range(args.steps):
        ids, label = synthetic_clicks(rng, args.batch, args.rows,
                                      args.slots)
        out, = exe.run(feed={"ids": ids, "label": label},
                       fetch_list=[loss])
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(np.ravel(out)[0]):.4f}")
        if cache is not None and step == args.steps // 2 - 1:
            # steady-state boundary: the first half pays the compulsory
            # misses of an empty cache, the floor speaks to steady state
            steady_base = cache.stats()

    if args.sharded:
        per = emb_mod.per_shard_table_bytes(main_prog)
        t = per["tables"]["emb_table"]
        print(f"table bytes {t['bytes']} -> {t['per_shard_bytes']} "
              f"per shard (factor {t['factor']}); adam moments "
              f"{t['opt_state_bytes']} -> {t['opt_state_per_shard_bytes']}")
    applied = telemetry.read_series("sparse_apply_rows_total")
    densified = telemetry.read_series("sparse_densify_fallback_total")
    print(f"scatter-applied rows: {applied}")
    print(f"densify fallbacks (should be empty): {densified or '{}'}")

    hit_rate_ok = True
    if cache is not None:
        s = cache.stats()
        b = steady_base or {"hits": 0, "misses": 0,
                            "compulsory_misses": 0}
        d_hit = s["hits"] - b["hits"]
        d_miss = s["misses"] - b["misses"]
        # the floor judges the EVICTION POLICY, so compulsory (first
        # ever touch) misses leave the denominator — a short run keeps
        # discovering tail ids long past the warmup boundary, and no
        # policy could have kept a row it never saw
        d_cap = d_miss - (s["compulsory_misses"]
                          - b["compulsory_misses"])
        rate = d_hit / max(d_hit + d_cap, 1)
        total_rate = d_hit / max(d_hit + d_miss, 1)
        flushed = cache.flush()
        print(f"steady-state hit rate {total_rate:.3f} raw, "
              f"{rate:.3f} vs capacity misses (floor {floor:.3f}); "
              f"evictions {s['evictions']}; final dirty-row flush "
              f"{flushed} bytes")
        if rate < floor:
            print(f"FAIL: capacity-miss hit rate {rate:.3f} below the "
                  f"analytic zipf floor {floor:.3f} — eviction policy "
                  f"regression", file=sys.stderr)
            hit_rate_ok = False
    return 0 if not densified and hit_rate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
