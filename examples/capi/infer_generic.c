/* Generic C inference driver: load any model saved by
 * fluid.io.save_inference_model and run one forward pass (reference:
 * paddle/capi/examples/model_inference/{dense,sparse_binary,multi_thread}
 * generalized — every book chapter's saved artifact goes through this
 * path, the way the reference's C++ book inference tests do:
 * paddle/fluid/inference/tests/book/test_inference_fit_a_line.cc + 7
 * siblings).
 *
 * Usage: infer_generic <model_dir> <input_spec>...
 * input_spec := name:dtype:d0xd1[xd2[xd3]][:mod=M][:lod=o0,o1,...]
 *   dtype = f32 | i64 | i32
 *   f32 fill pattern: x[i] = sin(0.01*i + slot)        (slot = spec index)
 *   int fill pattern: x[i] = (7*i + 3*slot) % M        (mod=M required)
 *   lod   = level-1 sequence start offsets (sequence inputs)
 * The Python side reproduces the same patterns to compare outputs.
 *
 * Build:
 *   gcc infer_generic.c -I paddle_tpu/native -L paddle_tpu/native \
 *       -lpaddle_tpu_capi -lm -o infer_generic
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "capi.h"

#define CHECK(stmt)                                          \
  do {                                                       \
    paddle_error e__ = (stmt);                               \
    if (e__ != PD_NO_ERROR) {                                \
      fprintf(stderr, "error %d at %s\n", e__, #stmt);       \
      return 1;                                              \
    }                                                        \
  } while (0)

static int stage_input(paddle_tpu_machine machine, char* spec, int slot) {
  /* tokenize name:dtype:dims[:mod=M][:lod=...] */
  char* save = NULL;
  char* name = strtok_r(spec, ":", &save);
  char* dtype_s = strtok_r(NULL, ":", &save);
  char* dims_s = strtok_r(NULL, ":", &save);
  if (!name || !dtype_s || !dims_s) {
    fprintf(stderr, "bad input spec (need name:dtype:dims)\n");
    return 1;
  }
  long long mod = 0;
  char* lod_s = NULL;
  char* extra;
  while ((extra = strtok_r(NULL, ":", &save)) != NULL) {
    if (strncmp(extra, "mod=", 4) == 0) mod = atoll(extra + 4);
    else if (strncmp(extra, "lod=", 4) == 0) lod_s = extra + 4;
  }

  int64_t dims[4];
  int ndim = 0;
  int64_t numel = 1;
  char* dsave = NULL;
  char* d = strtok_r(dims_s, "x", &dsave);
  for (; d && ndim < 4; d = strtok_r(NULL, "x", &dsave)) {
    dims[ndim] = atoll(d);
    numel *= dims[ndim];
    ndim++;
  }
  if (d != NULL) { /* more than 4 dims: fail loudly, never truncate */
    fprintf(stderr, "input %s: more than 4 dims in spec (got extra '%s')\n",
            name, d);
    return 1;
  }

  paddle_tpu_dtype dt;
  if (strcmp(dtype_s, "f32") == 0) dt = PD_DTYPE_FLOAT32;
  else if (strcmp(dtype_s, "i64") == 0) dt = PD_DTYPE_INT64;
  else if (strcmp(dtype_s, "i32") == 0) dt = PD_DTYPE_INT32;
  else {
    fprintf(stderr, "bad dtype %s\n", dtype_s);
    return 1;
  }

  if (dt == PD_DTYPE_FLOAT32) {
    float* x = (float*)malloc(sizeof(float) * (size_t)numel);
    for (int64_t i = 0; i < numel; ++i)
      x[i] = (float)sin(0.01 * (double)i + (double)slot);
    CHECK(paddle_tpu_machine_set_input_typed(machine, name, x, dt, dims,
                                             ndim));
    free(x);
  } else {
    if (mod <= 0) {
      fprintf(stderr, "int input %s needs mod=M\n", name);
      return 1;
    }
    if (dt == PD_DTYPE_INT64) {
      int64_t* x = (int64_t*)malloc(sizeof(int64_t) * (size_t)numel);
      for (int64_t i = 0; i < numel; ++i) x[i] = (7 * i + 3 * slot) % mod;
      CHECK(paddle_tpu_machine_set_input_typed(machine, name, x, dt, dims,
                                               ndim));
      free(x);
    } else {
      int32_t* x = (int32_t*)malloc(sizeof(int32_t) * (size_t)numel);
      for (int64_t i = 0; i < numel; ++i)
        x[i] = (int32_t)((7 * i + 3 * slot) % mod);
      CHECK(paddle_tpu_machine_set_input_typed(machine, name, x, dt, dims,
                                               ndim));
      free(x);
    }
  }

  if (lod_s != NULL) {
    int64_t offs[64];
    int n = 0;
    char* lsave = NULL;
    char* o = strtok_r(lod_s, ",", &lsave);
    for (; o && n < 64; o = strtok_r(NULL, ",", &lsave))
      offs[n++] = atoll(o);
    if (o != NULL) { /* >64 offsets: fail loudly, never truncate */
      fprintf(stderr,
              "input %s: more than 64 lod offsets in spec (extra '%s')\n",
              name, o);
      return 1;
    }
    CHECK(paddle_tpu_machine_set_input_lod(machine, name, offs, n));
  }
  return 0;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: %s <model_dir> name:dtype:d0xd1[:mod=M][:lod=..] ...\n",
            argv[0]);
    return 2;
  }

  CHECK(paddle_tpu_init());
  paddle_tpu_machine machine;
  CHECK(paddle_tpu_machine_create(&machine, argv[1]));

  for (int a = 2; a < argc; ++a)
    if (stage_input(machine, argv[a], a - 2) != 0) return 1;

  CHECK(paddle_tpu_machine_forward(machine));

  int count = 0;
  CHECK(paddle_tpu_machine_output_count(machine, &count));
  for (int o = 0; o < count; ++o) {
    const float* out;
    const int64_t* out_dims;
    int ndim;
    CHECK(paddle_tpu_machine_get_output(machine, o, &out, &out_dims, &ndim));
    int64_t total = 1;
    printf("output %d ndim=%d shape=[", o, ndim);
    for (int d = 0; d < ndim; ++d) {
      total *= out_dims[d];
      printf(d ? ",%lld" : "%lld", (long long)out_dims[d]);
    }
    printf("]\n");
    for (int64_t i = 0; i < total; ++i)
      printf("out%d[%lld]=%.6f\n", o, (long long)i, out[i]);
  }

  CHECK(paddle_tpu_machine_destroy(machine));
  return 0;
}
