/* Generic C inference driver: load any single-float-input model saved by
 * fluid.io.save_inference_model and run one forward pass (reference:
 * paddle/capi/examples/model_inference/dense/main.c generalized — the
 * conv and sequence book models go through this same path).
 *
 * Usage: infer_generic <model_dir> <input_name> d0 d1 [d2 [d3]]
 * The input tensor is filled with the deterministic pattern
 * x[i] = sin(0.01 * i) so the Python side can reproduce it exactly.
 *
 * Build:
 *   gcc infer_generic.c -I paddle_tpu/native -L paddle_tpu/native \
 *       -lpaddle_tpu_capi -lm -o infer_generic
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "capi.h"

#define CHECK(stmt)                                          \
  do {                                                       \
    paddle_error e__ = (stmt);                               \
    if (e__ != PD_NO_ERROR) {                                \
      fprintf(stderr, "error %d at %s\n", e__, #stmt);       \
      return 1;                                              \
    }                                                        \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s <model_dir> <input_name> d0 d1 [d2 [d3]]\n",
            argv[0]);
    return 2;
  }
  int ndim_in = argc - 3;
  if (ndim_in > 4) ndim_in = 4;
  int64_t dims[4];
  int64_t numel = 1;
  int d;
  for (d = 0; d < ndim_in; ++d) {
    dims[d] = atoll(argv[3 + d]);
    numel *= dims[d];
  }

  CHECK(paddle_tpu_init());
  paddle_tpu_machine machine;
  CHECK(paddle_tpu_machine_create(&machine, argv[1]));

  float* x = (float*)malloc(sizeof(float) * (size_t)numel);
  int64_t i;
  for (i = 0; i < numel; ++i) x[i] = (float)sin(0.01 * (double)i);
  CHECK(paddle_tpu_machine_set_input(machine, argv[2], x, dims, ndim_in));
  free(x);

  CHECK(paddle_tpu_machine_forward(machine));

  int count = 0;
  CHECK(paddle_tpu_machine_output_count(machine, &count));
  const float* out;
  const int64_t* out_dims;
  int ndim;
  CHECK(paddle_tpu_machine_get_output(machine, 0, &out, &out_dims, &ndim));
  int64_t total = 1;
  printf("outputs=%d ndim=%d shape=[", count, ndim);
  for (d = 0; d < ndim; ++d) {
    total *= out_dims[d];
    printf(d ? ",%lld" : "%lld", (long long)out_dims[d]);
  }
  printf("]\n");
  for (i = 0; i < total; ++i) printf("out[%lld]=%.6f\n", (long long)i, out[i]);

  CHECK(paddle_tpu_machine_destroy(machine));
  return 0;
}
