/* C inference example: load a fit_a_line model saved by
 * fluid.io.save_inference_model and predict (reference:
 * paddle/capi/examples/model_inference/dense/main.c).
 *
 * Build:
 *   make -C paddle_tpu/native libpaddle_tpu_capi.so
 *   gcc infer_fit_a_line.c -I paddle_tpu/native -L paddle_tpu/native \
 *       -lpaddle_tpu_capi -o infer_fit_a_line
 * Run (interpreter deps resolved via PYTHONPATH):
 *   LD_LIBRARY_PATH=paddle_tpu/native ./infer_fit_a_line <model_dir>
 */
#include <stdio.h>
#include <stdlib.h>

#include "capi.h"

#define CHECK(stmt)                                          \
  do {                                                       \
    paddle_error e__ = (stmt);                               \
    if (e__ != PD_NO_ERROR) {                                \
      fprintf(stderr, "error %d at %s\n", e__, #stmt);       \
      return 1;                                              \
    }                                                        \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_dir>\n", argv[0]);
    return 2;
  }
  CHECK(paddle_tpu_init());

  paddle_tpu_machine machine;
  CHECK(paddle_tpu_machine_create(&machine, argv[1]));

  /* two rows of the 13-feature uci_housing input */
  float x[2][13];
  int i, j;
  for (i = 0; i < 2; ++i)
    for (j = 0; j < 13; ++j) x[i][j] = 0.1f * (float)(i + 1) * (float)j;
  int64_t dims[2] = {2, 13};
  CHECK(paddle_tpu_machine_set_input(machine, "x", &x[0][0], dims, 2));

  CHECK(paddle_tpu_machine_forward(machine));

  int count = 0;
  CHECK(paddle_tpu_machine_output_count(machine, &count));
  const float* out;
  const int64_t* out_dims;
  int ndim;
  CHECK(paddle_tpu_machine_get_output(machine, 0, &out, &out_dims, &ndim));
  printf("outputs=%d ndim=%d shape=[%lld,%lld]\n", count, ndim,
         (long long)out_dims[0], (long long)out_dims[1]);
  for (i = 0; i < (int)out_dims[0]; ++i) printf("pred[%d]=%.6f\n", i, out[i]);

  CHECK(paddle_tpu_machine_destroy(machine));
  return 0;
}
