#!/usr/bin/env python
"""Sentiment classification the v2 way — the reference's pre-fluid user
surface (reference demo style: data layers + networks.simple_lstm +
SGD event loop + infer), running unchanged over the fluid/XLA stack.

Run:  python examples/v2/sentiment_lstm.py
"""

import numpy as np

from paddle_tpu import v2 as paddle
from paddle_tpu.dataset import imdb


def main():
    vocab = len(imdb.word_dict())
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=64, vocab_size=vocab)
    lstm = paddle.networks.simple_lstm(input=emb, size=64)
    pooled = paddle.layer.pooling(lstm, pooling_type=paddle.pooling.Max)
    logits = paddle.layer.fc(input=pooled, size=2,
                             act=paddle.activation.Linear)
    cost = paddle.layer.classification_cost(input=logits, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    # the canonical reference composition: batch(shuffle(dataset.train()))
    train_reader = paddle.batch(
        paddle.reader.shuffle(
            paddle.reader.firstn(
                paddle.reader.map_readers(
                    lambda s: (s[0], [s[1]]), imdb.train()), 512),
            buf_size=256),
        batch_size=32)

    def handler(event):
        if isinstance(event, paddle.event.EndPass):
            print(f"pass {event.pass_id} done")
        elif isinstance(event, paddle.event.EndIteration) and \
                event.batch_id % 8 == 0:
            print(f"  batch {event.batch_id}: cost {event.cost:.4f}")

    trainer.train(train_reader, num_passes=3, event_handler=handler,
                  feeding={"words": 0, "label": 1})

    probe = [([5, 6, 7, 8],), ([3000, 3001, 3002],)]
    out = np.asarray(paddle.infer(output_layer=logits,
                                  parameters=parameters, input=probe,
                                  feeding={"words": 0}))
    print("inferred logits:", out)


if __name__ == "__main__":
    main()
