"""Device-side input pipeline tests (reference: test_recordio_reader.py,
operators/reader/create_double_buffer_reader_op.cc semantics)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import recordio
from paddle_tpu.reader.pipeline import DoubleBufferedFeeder

RNG = np.random.RandomState(17)


class TestDoubleBufferedFeeder:
    def test_yields_all_batches_in_order(self):
        batches = [{"x": np.full((2, 2), i, np.float32)} for i in range(7)]
        dbf = DoubleBufferedFeeder(lambda: iter(batches))
        got = [b["x"][0, 0] for b in dbf]
        assert got == list(range(7))
        # reiterating restarts the pass
        got2 = [b["x"][0, 0] for b in dbf]
        assert got2 == list(range(7))

    def test_propagates_reader_errors(self):
        def bad_reader():
            yield {"x": np.zeros(1)}
            raise ValueError("boom")

        dbf = DoubleBufferedFeeder(bad_reader)
        it = iter(dbf)
        next(it)
        with pytest.raises(ValueError, match="boom"):
            next(it)


class TestNextWindow:
    """next_window(k): the input half of the fused multi-step loop."""

    def _feeder(self, n):
        batches = [{"x": np.full((2, 3), i, np.float32),
                    "y": np.full((2, 1), -i, np.int64)} for i in range(n)]
        return DoubleBufferedFeeder(lambda: iter(batches))

    def test_stacks_k_batches_in_order(self):
        dbf = self._feeder(7)
        w = dbf.next_window(3)
        assert set(w) == {"x", "y"}
        assert w["x"].shape == (3, 2, 3) and w["y"].shape == (3, 2, 1)
        np.testing.assert_array_equal(w["x"][:, 0, 0], [0, 1, 2])
        # consecutive windows continue the SAME pass, no batch skipped
        w2 = dbf.next_window(3)
        np.testing.assert_array_equal(w2["x"][:, 0, 0], [3, 4, 5])

    def test_short_remainder_dropped_at_end_of_pass(self):
        from paddle_tpu import telemetry
        dbf = self._feeder(7)
        dbf.next_window(3)
        dbf.next_window(3)
        before = sum(telemetry.read_series(
            "input_window_dropped_batches_total").values())
        with pytest.raises(StopIteration):
            dbf.next_window(3)   # only batch 6 left: dropped, counted
        dropped = sum(telemetry.read_series(
            "input_window_dropped_batches_total").values()) - before
        assert dropped == 1
        # the feeder is reusable: a fresh pass starts from batch 0
        w = dbf.next_window(3)
        np.testing.assert_array_equal(w["x"][:, 0, 0], [0, 1, 2])

    def test_mismatched_feed_names_rejected(self):
        batches = [{"x": np.zeros((2, 3), np.float32)},
                   {"y": np.zeros((2, 3), np.float32)}]
        dbf = DoubleBufferedFeeder(lambda: iter(batches))
        with pytest.raises(ValueError, match="same feed names"):
            dbf.next_window(2)


class TestSparseSlots:
    """next_window(..., sparse_slots=[...]) — the emb_cache prefetch
    hook (ISSUE 14 satellite): the return becomes (window, {name:
    sorted unique-id union over the whole window}), the listed slots
    stay host numpy even when device= is passed (the cache remaps them
    before the device ever sees them), and batch accounting (order,
    dedup, dropped remainder) is byte-identical to the plain path."""

    def _feeder(self, n, depth=1):
        # known overlapping ids: batch i holds {i, i+1, 7}
        batches = [{"ids": np.array([[i], [i + 1], [7]], np.int64),
                    "lab": np.full((3, 1), float(i), np.float32)}
                   for i in range(n)]
        return DoubleBufferedFeeder(lambda: iter(batches),
                                    window_prefetch=depth)

    @pytest.mark.parametrize("depth", [1, 2])
    def test_union_is_sorted_unique_over_window(self, depth):
        dbf = self._feeder(7, depth)
        win, uniq = dbf.next_window(3, sparse_slots=["ids"])
        assert set(uniq) == {"ids"}
        # batches 0,1,2 -> ids {0,1,7} u {1,2,7} u {2,3,7}
        np.testing.assert_array_equal(uniq["ids"], [0, 1, 2, 3, 7])
        assert win["ids"].shape == (3, 3, 1)
        # non-listed slots are untouched; listed slot stays host numpy
        assert isinstance(win["ids"], np.ndarray)
        # the SAME pass continues — dedup consumed no extra batches
        win2, uniq2 = dbf.next_window(3, sparse_slots=["ids"])
        np.testing.assert_array_equal(win2["lab"][:, 0, 0], [3, 4, 5])
        np.testing.assert_array_equal(uniq2["ids"], [3, 4, 5, 6, 7])
        dbf.stop()

    @pytest.mark.parametrize("depth", [1, 2])
    def test_remainder_accounting_unchanged(self, depth):
        from paddle_tpu import telemetry
        # snapshot BEFORE the feeder exists: under window_prefetch the
        # builder thread counts the drop as soon as it exhausts the
        # pass, which can precede the consumer's StopIteration
        before = sum(telemetry.read_series(
            "input_window_dropped_batches_total").values())
        dbf = self._feeder(7, depth)
        dbf.next_window(3, sparse_slots=["ids"])
        dbf.next_window(3, sparse_slots=["ids"])
        with pytest.raises(StopIteration):
            dbf.next_window(3, sparse_slots=["ids"])
        dropped = sum(telemetry.read_series(
            "input_window_dropped_batches_total").values()) - before
        assert dropped == 1    # only batch 6 was left on this pass
        # reusable: fresh pass restarts at batch 0, union included
        win, uniq = dbf.next_window(3, sparse_slots=["ids"])
        np.testing.assert_array_equal(win["lab"][:, 0, 0], [0, 1, 2])
        np.testing.assert_array_equal(uniq["ids"], [0, 1, 2, 3, 7])
        dbf.stop()

    def test_missing_slot_name_ignored(self):
        dbf = self._feeder(3)
        _, uniq = dbf.next_window(3, sparse_slots=["ids", "absent"])
        assert set(uniq) == {"ids"}


class TestWindowPrefetch:
    """window_prefetch > 1 (ISSUE 9 satellite): the stack + device_put
    moves to a background window-builder thread; the stream must stay
    identical to the synchronous path — same windows, same order, same
    dropped-remainder accounting."""

    def _feeder(self, n, depth):
        batches = [{"x": np.full((2, 3), i, np.float32)} for i in range(n)]
        return DoubleBufferedFeeder(lambda: iter(batches),
                                    window_prefetch=depth)

    def test_same_stream_as_synchronous(self):
        sync, pre = self._feeder(9, 1), self._feeder(9, 3)
        for _ in range(3):
            a, b = sync.next_window(3), pre.next_window(3)
            np.testing.assert_array_equal(a["x"], b["x"])
        pre.stop()

    def test_remainder_dropped_and_counted(self):
        from paddle_tpu import telemetry
        dbf = self._feeder(7, 2)
        dbf.next_window(3)
        dbf.next_window(3)
        before = sum(telemetry.read_series(
            "input_window_dropped_batches_total").values())
        with pytest.raises(StopIteration):
            dbf.next_window(3)   # only batch 6 left on this pass
        dropped = sum(telemetry.read_series(
            "input_window_dropped_batches_total").values()) - before
        assert dropped == 1
        # reusable: the next call starts a fresh pass from batch 0
        w = dbf.next_window(3)
        np.testing.assert_array_equal(w["x"][:, 0, 0], [0, 1, 2])
        dbf.stop()

    def test_reader_error_surfaces_in_consumer(self):
        def bad_reader():
            yield {"x": np.zeros((1,), np.float32)}
            raise ValueError("boom")

        dbf = DoubleBufferedFeeder(bad_reader, window_prefetch=2)
        with pytest.raises(ValueError, match="boom"):
            dbf.next_window(2)
        dbf.stop()

    def test_stop_terminates_builder_thread(self):
        dbf = self._feeder(50, 2)
        dbf.next_window(2)
        t = dbf._wthread
        assert t is not None and t.is_alive()
        dbf.stop()
        assert dbf._wthread is None
        t.join(timeout=5)
        assert not t.is_alive()


class TestFeedWindow:
    def test_data_feeder_feed_window(self):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            fluid.layers.data(name="x", shape=[3], dtype="float32")
            fluid.layers.data(name="y", shape=[1], dtype="int64")
            feeder = fluid.DataFeeder(["x", "y"], fluid.CPUPlace(),
                                      program=prog)
        mbs = [[(np.full(3, i, np.float32), [i]) for i in (0, 1)],
               [(np.full(3, i, np.float32), [i]) for i in (2, 3)]]
        w = feeder.feed_window(mbs)
        assert w["x"].shape == (2, 2, 3) and w["y"].shape == (2, 2, 1)
        np.testing.assert_array_equal(w["y"][:, :, 0], [[0, 1], [2, 3]])

    def test_feed_window_rejects_lod(self):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            fluid.layers.data(name="seq", shape=[1], dtype="int64",
                              lod_level=1)
            feeder = fluid.DataFeeder(["seq"], fluid.CPUPlace(),
                                      program=prog)
        mbs = [[([1, 2],), ([3],)]]
        with pytest.raises(ValueError, match="LoD"):
            feeder.feed_window(mbs)


class TestRecordIOReaderPipeline:
    def _write_dataset(self, path, n=32):
        def samples():
            for i in range(n):
                x = RNG.rand(4).astype(np.float32)
                y = np.array([int(x.sum() > 2.0)], np.int64)
                yield (x, y)
        recordio.write_samples(path, samples())

    def test_reader_driven_training(self, tmp_path):
        """Full parity loop: open_recordio_file -> shuffle -> batch ->
        double_buffer -> read_file; exe.run() with no feed pulls batches
        until EOFException (reference book-test reader idiom)."""
        path = str(tmp_path / "train.recordio")
        self._write_dataset(path)

        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            reader = fluid.layers.open_recordio_file(
                path, shapes=[[-1, 4], [-1, 1]], lod_levels=[0, 0],
                dtypes=["float32", "int64"])
            reader = fluid.layers.shuffle(reader, buffer_size=16)
            reader = fluid.layers.batch(reader, batch_size=8)
            reader = fluid.layers.double_buffer(reader)
            x, y = fluid.layers.read_file(reader)
            pred = fluid.layers.fc(input=x, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(pred, y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            seen = 0
            for _pass in range(2):
                while True:
                    try:
                        v, = exe.run(main, fetch_list=[loss])
                    except fluid.layers.EOFException:
                        reader.reset()
                        break
                    seen += 1
                    assert np.isfinite(np.asarray(v)).all()
            assert seen == 2 * (32 // 8)

    def test_lod_slot_batching(self, tmp_path):
        """Variable-length slots come out of batch() as LoDTensors and feed
        the padded-LoD path."""
        path = str(tmp_path / "seq.recordio")
        rows = [RNG.rand(n, 3).astype(np.float32) for n in (2, 4, 1, 3)]
        recordio.write_samples(path, [(r,) for r in rows])

        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            reader = fluid.layers.open_recordio_file(
                path, shapes=[[-1, 3]], lod_levels=[1], dtypes=["float32"])
            reader = fluid.layers.batch(reader, batch_size=4)
            seq = fluid.layers.read_file(reader)
            pooled = fluid.layers.sequence_pool(seq, "sum")

        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            got, = exe.run(main, fetch_list=[pooled])
        want = np.stack([r.sum(0) for r in rows])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


class TestMultiPass:
    def test_multi_pass_repeats_stream(self, tmp_path):
        import os
        from paddle_tpu import recordio as recordio_mod
        path = os.path.join(str(tmp_path), "mp.recordio")
        recordio_mod.write_samples(
            path, [(np.full((2,), i, np.float32),) for i in range(3)])
        r = fluid.layers.open_recordio_file(
            path, shapes=[[2]], lod_levels=[0], dtypes=["float32"])
        r = fluid.layers.multi_pass(r, pass_num=2)
        r = fluid.layers.batch(r, batch_size=1)
        vals = []
        try:
            while True:
                (b,) = r.next_batch()
                vals.append(int(np.asarray(b).ravel()[0]))
        except fluid.layers.EOFException:
            pass
        assert vals == [0, 1, 2, 0, 1, 2], vals
