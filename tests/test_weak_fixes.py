"""Round-2 hardening: multi-target calc_gradient, control-flow-aware prune
(save_inference_model through a While), jit-path NaN/Inf check (reference:
backward.py:555 calc_gradient, prune.cc:181 recursion, executor.cc:325-333
FLAGS_check_nan_inf)."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod


def run_prog(feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed, fetch_list=fetch)


class TestCalcGradient:
    def test_single_target(self):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                                  append_batch_size=False,
                                  stop_gradient=False)
            y = fluid.layers.reduce_sum(fluid.layers.elementwise_mul(x, x))
            (gx,) = fluid.calc_gradient(y, x)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                xv = np.array([1.0, -2.0, 3.0], np.float32)
                g, = exe.run(fluid.default_main_program(), feed={"x": xv},
                             fetch_list=[gx])
        np.testing.assert_allclose(np.asarray(g), 2 * xv, rtol=1e-6)

    def test_multi_target_with_cotangents(self):
        """grad of <tg1, t1> + <tg2, t2> — the reference's multi-target
        semantics (test_calc_gradient.py)."""
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                                  append_batch_size=False,
                                  stop_gradient=False)
            t1 = fluid.layers.scale(x, scale=3.0)       # dt1/dx = 3
            t2 = fluid.layers.elementwise_mul(x, x)     # dt2/dx = 2x
            tg1 = fluid.layers.fill_constant([2], "float32", 2.0)
            tg2 = fluid.layers.fill_constant([2], "float32", 0.5)
            (gx,) = fluid.calc_gradient([t1, t2], x,
                                        target_gradients=[tg1, tg2])
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                xv = np.array([1.0, -4.0], np.float32)
                g, = exe.run(fluid.default_main_program(), feed={"x": xv},
                             fetch_list=[gx])
        want = 2.0 * 3.0 + 0.5 * 2 * xv
        np.testing.assert_allclose(np.asarray(g), want, rtol=1e-6)


class TestPruneThroughControlFlow:
    def _build(self):
        x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                              append_batch_size=False)
        # upstream op whose output is consumed ONLY inside the while body
        doubled = fluid.layers.scale(x, scale=2.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64", value=3)
        acc = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        cond = fluid.layers.less_than(x=i, y=limit)
        w = fluid.layers.While(cond=cond)
        with w.block():
            new_acc = fluid.layers.elementwise_add(acc, doubled)
            fluid.layers.assign(new_acc, acc)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        # decoy op that must be pruned away
        decoy = fluid.layers.scale(x, scale=100.0)
        return x, acc, decoy

    def test_prune_keeps_subblock_producers(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x, acc, decoy = self._build()
        pruned = main.prune(feeds=["x"], fetches=[acc.name])
        kept_types = [op.type for op in pruned.global_block().ops]
        # the producer feeding the while body must survive
        assert kept_types.count("scale") == 1, kept_types
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            got, = exe.run(pruned, feed={"x": np.array([5.0], np.float32)},
                           fetch_list=[acc.name])
        assert float(np.asarray(got)[0]) == 30.0   # 3 iterations of +10

    def test_save_load_inference_model_with_while(self, tmp_path):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x, acc, _ = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            want, = exe.run(main, feed={"x": np.array([2.0], np.float32)},
                            fetch_list=[acc.name])
            fluid.io.save_inference_model(str(tmp_path), ["x"], [acc], exe,
                                          main_program=main)
        with executor_mod.scope_guard(executor_mod.Scope()):
            prog, feed_names, fetch_targets = \
                fluid.io.load_inference_model(str(tmp_path), exe)
            got, = exe.run(prog, feed={"x": np.array([2.0], np.float32)},
                           fetch_list=fetch_targets)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


class TestJitNanCheck:
    def test_nan_raises_with_var_name(self, monkeypatch):
        monkeypatch.setattr(executor_mod, "_CHECK_NAN_INF", True)
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.log(x)    # log(-1) = nan
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                with pytest.raises(RuntimeError, match="NaN/Inf.*'"):
                    exe.run(fluid.default_main_program(),
                            feed={"x": np.array([-1.0, 1.0], np.float32)},
                            fetch_list=[y])


class TestEnforceStyleErrors:
    def test_lowering_failure_names_op_and_shapes(self):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[2, 3], dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.data(name="y", shape=[4, 5], dtype="float32",
                                  append_batch_size=False)
            out = fluid.layers.elementwise_add(x, y)   # incompatible
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                with pytest.raises(RuntimeError) as ei:
                    exe.run(fluid.default_main_program(),
                            feed={"x": np.zeros((2, 3), np.float32),
                                  "y": np.zeros((4, 5), np.float32)},
                            fetch_list=[out])
        msg = str(ei.value)
        assert "elementwise_add" in msg and "input shapes" in msg
