"""Child process for test_fleet's cross-host skew test: a real 2-process
jax.distributed bring-up (same harness as _telemetry_worker.py) where each
process publishes a distinct last-step time — and the slow one a large
infeed wait — then asserts fleet.fleet_snapshot() reduces to the same
skew / straggler verdict on BOTH sides.

Run as:  python _fleet_worker.py <coordinator> <nprocs> <pid>

Prints one line `RESULT <json>` on success."""

import json
import os
import sys


def main(coordinator, nprocs, pid):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS", "")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu import fleet, telemetry
    from paddle_tpu.parallel import multihost

    assert multihost.initialize(coordinator_address=coordinator,
                                num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    assert telemetry._host_index() == pid

    # distinct per-host profile: the last host is the slowest, and its
    # excess badput is infeed wait — the reduce must name both
    telemetry.gauge("executor_last_step_seconds",
                    "wall seconds of the last executed step").set(
                        0.1 * (pid + 1))
    if pid == nprocs - 1:
        telemetry.histogram("input_stall_seconds",
                            "reader wait per step").observe(0.5)

    snap = fleet.fleet_snapshot()
    assert snap["n_hosts"] == nprocs, snap
    assert abs(snap["max_step_s"] - 0.1 * nprocs) < 1e-9, snap
    want_skew = (0.1 * nprocs) / snap["median_step_s"]
    assert abs(snap["step_skew"] - want_skew) < 1e-9, snap
    assert snap["straggler"]["host"] == nprocs - 1, snap
    assert snap["straggler"]["cause"] == "infeed", snap

    # the reduce published the fleet gauges locally on every host
    assert telemetry.read_gauge("fleet_step_skew") == snap["step_skew"]
    assert telemetry.read_gauge("fleet_straggler_host") == float(nprocs - 1)

    print("RESULT " + json.dumps(
        {"pid": pid, "skew": snap["step_skew"],
         "straggler": snap["straggler"]}), flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
