"""Vision op family tests (reference: test_pool3d_op.py, test_pool_max_op.py,
test_unpool_op.py, test_spp_op.py, test_roi_pool_op.py, test_crop_op.py,
test_conv3d_transpose_op.py, test_prelu_op.py, test_conv_shift_op.py)."""

import itertools
import math

import numpy as np

from op_test import OpTest

RNG = np.random.RandomState(42)


def well_separated(*shape):
    """Distinct values with pairwise gaps >> the numeric-grad delta, so
    max-pool argmaxes cannot flip under perturbation."""
    n = int(np.prod(shape))
    return (RNG.permutation(n).astype("float32") / n).reshape(shape)


def _pool3d_np(x, k, s, p, ptype, exclusive=True):
    n, c, d, h, w = x.shape
    od = (d - k[0] + 2 * p[0]) // s[0] + 1
    oh = (h - k[1] + 2 * p[1]) // s[1] + 1
    ow = (w - k[2] + 2 * p[2]) // s[2] + 1
    out = np.zeros((n, c, od, oh, ow), x.dtype)
    for zd, zh, zw in itertools.product(range(od), range(oh), range(ow)):
        d0, d1 = max(zd * s[0] - p[0], 0), min(zd * s[0] - p[0] + k[0], d)
        h0, h1 = max(zh * s[1] - p[1], 0), min(zh * s[1] - p[1] + k[1], h)
        w0, w1 = max(zw * s[2] - p[2], 0), min(zw * s[2] - p[2] + k[2], w)
        win = x[:, :, d0:d1, h0:h1, w0:w1]
        if ptype == "max":
            out[:, :, zd, zh, zw] = win.max(axis=(2, 3, 4))
        else:
            denom = win[0, 0].size if exclusive else k[0] * k[1] * k[2]
            out[:, :, zd, zh, zw] = win.sum(axis=(2, 3, 4)) / denom
    return out


class TestPool3dMax(OpTest):
    op_type = "pool3d"

    def setup(self):
        x = np.random.rand(2, 3, 5, 6, 5).astype("float32")
        self.attrs = {"pooling_type": "max", "ksize": [2, 3, 2],
                      "strides": [1, 2, 2], "paddings": [0, 1, 0]}
        self.inputs = {"X": x}
        self.outputs = {"Out": _pool3d_np(x, [2, 3, 2], [1, 2, 2],
                                          [0, 1, 0], "max")}

    def test(self):
        self.setup()
        self.check_output()
        self.inputs["X"] = well_separated(1, 1, 3, 4, 3)
        self.outputs["Out"] = _pool3d_np(self.inputs["X"], [2, 3, 2],
                                         [1, 2, 2], [0, 1, 0], "max")
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestPool3dAvg(OpTest):
    op_type = "pool3d"

    def test(self):
        x = np.random.rand(2, 2, 4, 5, 4).astype("float32")
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2, 2],
                      "strides": [2, 2, 2], "paddings": [0, 0, 0],
                      "exclusive": False}
        self.inputs = {"X": x}
        self.outputs = {"Out": _pool3d_np(x, [2, 2, 2], [2, 2, 2],
                                          [0, 0, 0], "avg", exclusive=False)}
        self.check_output()


def _max_pool2d_index_np(x, k, s, p):
    n, c, h, w = x.shape
    oh = (h - k[0] + 2 * p[0]) // s[0] + 1
    ow = (w - k[1] + 2 * p[1]) // s[1] + 1
    out = np.zeros((n, c, oh, ow), x.dtype)
    mask = np.zeros((n, c, oh, ow), "int32")
    for zh, zw in itertools.product(range(oh), range(ow)):
        h0, h1 = max(zh * s[0] - p[0], 0), min(zh * s[0] - p[0] + k[0], h)
        w0, w1 = max(zw * s[1] - p[1], 0), min(zw * s[1] - p[1] + k[1], w)
        win = x[:, :, h0:h1, w0:w1].reshape(n, c, -1)
        am = win.argmax(axis=2)
        out[:, :, zh, zw] = win.max(axis=2)
        wlen = w1 - w0
        mask[:, :, zh, zw] = (h0 + am // wlen) * w + (w0 + am % wlen)
    return out, mask


class TestMaxPool2dWithIndex(OpTest):
    op_type = "max_pool2d_with_index"

    def test(self):
        x = np.random.rand(2, 3, 6, 6).astype("float32")
        k, s, p = [3, 3], [2, 2], [1, 1]
        out, mask = _max_pool2d_index_np(x, k, s, p)
        self.inputs = {"X": x}
        self.attrs = {"ksize": k, "strides": s, "paddings": p}
        self.outputs = {"Out": out, "Mask": mask}
        self.check_output()
        self.inputs["X"] = well_separated(1, 2, 4, 4)
        o2, m2 = _max_pool2d_index_np(self.inputs["X"], k, s, p)
        self.outputs = {"Out": o2, "Mask": m2}
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestMaxPool3dWithIndex(OpTest):
    op_type = "max_pool3d_with_index"

    def test(self):
        x = np.random.rand(1, 2, 4, 5, 4).astype("float32")
        k, s, p = [2, 2, 2], [2, 2, 2], [0, 0, 0]
        n, c, d, h, w = x.shape
        od, oh, ow = d // 2, (h - 2) // 2 + 1, w // 2
        out = np.zeros((n, c, od, oh, ow), x.dtype)
        mask = np.zeros((n, c, od, oh, ow), "int32")
        for zd, zh, zw in itertools.product(range(od), range(oh), range(ow)):
            win = x[:, :, zd*2:zd*2+2, zh*2:zh*2+2, zw*2:zw*2+2]
            flat = win.reshape(n, c, -1)
            am = flat.argmax(axis=2)
            out[:, :, zd, zh, zw] = flat.max(axis=2)
            di = zd * 2 + am // 4
            hi = zh * 2 + (am % 4) // 2
            wi = zw * 2 + am % 2
            mask[:, :, zd, zh, zw] = (di * h + hi) * w + wi
        self.inputs = {"X": x}
        self.attrs = {"ksize": k, "strides": s, "paddings": p}
        self.outputs = {"Out": out, "Mask": mask}
        self.check_output()


class TestUnpool(OpTest):
    op_type = "unpool"

    def test(self):
        x = np.random.rand(1, 2, 2, 2).astype("float32")
        # valid disjoint flat indices into a 4x4 plane
        idx = np.array([[[[0, 3], [9, 14]], [[1, 6], [8, 15]]]], "int32")
        out = np.zeros((1, 2, 4, 4), "float32")
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    f = idx[0, c, i, j]
                    out[0, c, f // 4, f % 4] = x[0, c, i, j]
        self.inputs = {"X": x, "Indices": idx}
        self.attrs = {"unpooled_size": [4, 4]}
        self.outputs = {"Out": out}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSpp(OpTest):
    op_type = "spp"

    def test(self):
        x = np.random.rand(2, 3, 7, 9).astype("float32")
        n, c, h, w = x.shape
        levels = []
        for level in range(2):
            b = 2 ** level
            kh, kw = math.ceil(h / b), math.ceil(w / b)
            ph = (kh * b - h + 1) // 2
            pw = (kw * b - w + 1) // 2
            o = np.full((n, c, b, b), -np.inf, "float32")
            for zh, zw in itertools.product(range(b), range(b)):
                h0, h1 = max(zh * kh - ph, 0), min(zh * kh - ph + kh, h)
                w0, w1 = max(zw * kw - pw, 0), min(zw * kw - pw + kw, w)
                o[:, :, zh, zw] = x[:, :, h0:h1, w0:w1].max(axis=(2, 3))
            levels.append(o.reshape(n, -1))
        self.inputs = {"X": x}
        self.attrs = {"pyramid_height": 2, "pooling_type": "max"}
        self.outputs = {"Out": np.concatenate(levels, axis=1)}
        self.check_output()


def _roi_pool_np(x, rois, bid, scale, ph, pw):
    n, c, h, w = x.shape
    r = rois.shape[0]
    out = np.zeros((r, c, ph, pw), x.dtype)
    for ri in range(r):
        x1, y1, x2, y2 = np.round(rois[ri] * scale).astype(int)
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for pi in range(ph):
            for pj in range(pw):
                h0 = min(max(y1 + (pi * rh) // ph, 0), h)
                h1 = min(max(y1 + ((pi + 1) * rh + ph - 1) // ph, 0), h)
                w0 = min(max(x1 + (pj * rw) // pw, 0), w)
                w1 = min(max(x1 + ((pj + 1) * rw + pw - 1) // pw, 0), w)
                if h1 > h0 and w1 > w0:
                    out[ri, :, pi, pj] = \
                        x[bid[ri], :, h0:h1, w0:w1].max(axis=(1, 2))
    return out


class TestRoiPool(OpTest):
    op_type = "roi_pool"

    def test(self):
        x = np.random.rand(2, 3, 8, 8).astype("float32")
        rois = np.array([[1, 1, 6, 6], [0, 2, 7, 7], [2, 0, 3, 3]], "float32")
        bid = np.array([0, 1, 1], "int32")
        out = _roi_pool_np(x, rois, bid, 1.0, 3, 3)
        self.inputs = {"X": x, "ROIs": rois, "RoiBatchId": bid}
        self.attrs = {"spatial_scale": 1.0, "pooled_height": 3,
                      "pooled_width": 3}
        self.outputs = {"Out": out}
        self.check_output(no_check_set=("Argmax",))
        # grad: tiny case
        self.inputs = {"X": well_separated(1, 1, 4, 4),
                       "ROIs": np.array([[0, 0, 3, 3]], "float32"),
                       "RoiBatchId": np.array([0], "int32")}
        self.attrs = {"spatial_scale": 1.0, "pooled_height": 2,
                      "pooled_width": 2}
        self.outputs = {"Out": _roi_pool_np(
            self.inputs["X"], self.inputs["ROIs"],
            self.inputs["RoiBatchId"], 1.0, 2, 2)}
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestCrop(OpTest):
    op_type = "crop"

    def test(self):
        x = np.random.rand(4, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [2, 3], "offsets": [1, 2]}
        self.outputs = {"Out": x[1:3, 2:5]}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestCropWithOffsetsInput(OpTest):
    op_type = "crop"

    def test(self):
        x = np.random.rand(4, 6).astype("float32")
        off = np.array([1, 2], "int32")
        self.inputs = {"X": x, "Offsets": off}
        self.attrs = {"shape": [2, 3]}
        self.outputs = {"Out": x[1:3, 2:5]}
        self.check_output()


def _conv3d_transpose_np(x, w, s, p):
    n, cin, d, h, wd = x.shape
    _, cout, kd, kh, kw = w.shape
    od = s[0] * (d - 1) + kd - 2 * p[0]
    oh = s[1] * (h - 1) + kh - 2 * p[1]
    ow = s[2] * (wd - 1) + kw - 2 * p[2]
    out = np.zeros((n, cout, od + 2 * p[0], oh + 2 * p[1], ow + 2 * p[2]),
                   x.dtype)
    for ni, ci, zd, zh, zw in itertools.product(
            range(n), range(cin), range(d), range(h), range(wd)):
        out[ni, :, zd*s[0]:zd*s[0]+kd, zh*s[1]:zh*s[1]+kh,
            zw*s[2]:zw*s[2]+kw] += x[ni, ci, zd, zh, zw] * w[ci]
    if p != [0, 0, 0]:
        out = out[:, :, p[0]:p[0]+od, p[1]:p[1]+oh, p[2]:p[2]+ow]
    return out


class TestConv3dTranspose(OpTest):
    op_type = "conv3d_transpose"

    def test(self):
        x = np.random.rand(1, 2, 3, 3, 3).astype("float32")
        w = np.random.rand(2, 3, 2, 2, 2).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2, 2], "paddings": [1, 1, 1]}
        self.outputs = {"Output": _conv3d_transpose_np(
            x, w, [2, 2, 2], [1, 1, 1])}
        self.check_output(atol=1e-4)
        self.inputs = {"Input": np.random.rand(1, 1, 2, 2, 2).astype("float32"),
                       "Filter": np.random.rand(1, 1, 2, 2, 2).astype("float32")}
        self.attrs = {"strides": [1, 1, 1], "paddings": [0, 0, 0]}
        self.outputs = {"Output": _conv3d_transpose_np(
            self.inputs["Input"], self.inputs["Filter"],
            [1, 1, 1], [0, 0, 0])}
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.02)


class TestPrelu(OpTest):
    op_type = "prelu"

    def test(self):
        x = (np.random.rand(3, 4) - 0.5).astype("float32")
        x[np.abs(x) < 0.05] = 0.1   # keep away from the kink
        # element-mode alpha is [1, *feature_dims]: one alpha per feature
        # element shared across the batch (a parameter cannot be sized by
        # the -1 batch dim)
        for mode, a in (("all", np.array([0.25], "float32")),
                        ("channel", np.random.rand(4).astype("float32")),
                        ("element", np.random.rand(1, 4).astype("float32"))):
            alpha = a.reshape(-1) if mode != "element" else a
            if mode == "all":
                ab = a[0]
            elif mode == "channel":
                ab = a[None, :]
            else:
                ab = a
            self.inputs = {"X": x, "Alpha": alpha}
            self.attrs = {"mode": mode}
            self.outputs = {"Out": np.where(x > 0, x, ab * x)}
            self.check_output()
        self.check_grad(["X", "Alpha"], "Out")


class TestConvShift(OpTest):
    op_type = "conv_shift"

    def test(self):
        b, m, n = 2, 7, 3
        x = np.random.rand(b, m).astype("float32")
        y = np.random.rand(b, n).astype("float32")
        out = np.zeros_like(x)
        for i in range(m):
            for j in range(n):
                out[:, i] += x[:, (i + j - n // 2) % m] * y[:, j]
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": out}
        self.check_output()
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.02)
