"""Oracle tests for the second r5 v2 wrapper tranche (multiplex, row_conv,
spp, block_expand, conv_shift, seq_slice/sub_seq, kmax_seq_score,
get_output, cross_entropy_with_selfnorm, lambda_cost) and the F15
channel surface re-export."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.v2 import layer as v2l


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(fluid.default_startup_program())
        outs = exe.run(feed=feed, fetch_list=list(fetch))
    return [np.asarray(o) for o in outs]


def _data(name, shape, dtype="float32"):
    return fluid.layers.data(name=name, shape=shape, dtype=dtype,
                             append_batch_size=False)


RNG = np.random.RandomState(11)


class TestTrancheTwo:
    def test_multiplex(self):
        a, b = _data("a", [4, 3]), _data("b", [4, 3])
        idx = _data("idx", [4, 1], dtype="int64")
        out = v2l.multiplex([a, b], index=idx)
        av = RNG.randn(4, 3).astype(np.float32)
        bv = RNG.randn(4, 3).astype(np.float32)
        iv = np.array([[0], [1], [1], [0]], np.int64)
        got, = _run([out], {"a": av, "b": bv, "idx": iv})
        want = np.where(iv == 0, av, bv)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_spp_output_width(self):
        img = _data("img", [2, 3, 8, 8])
        out = v2l.spp(img, pyramid_height=2)
        got, = _run([out], {"img": RNG.randn(2, 3, 8, 8)
                            .astype(np.float32)})
        # pyramid levels 1x1 + 2x2 = 5 bins per channel
        assert got.shape == (2, 3 * 5)

    def test_block_expand_shapes(self):
        img = _data("img", [2, 1, 4, 6])
        out = v2l.block_expand(img, block_x=3, block_y=2, stride_x=3,
                               stride_y=2)
        got, = _run([out], {"img": RNG.randn(2, 1, 4, 6)
                            .astype(np.float32)})
        # (4/2) * (6/3) = 4 blocks per image, each 1*2*3=6 wide
        assert got.shape[-1] == 6
        assert got.shape[0] == 2 * 4

    def test_conv_shift_circular_correlation(self):
        a, b = _data("a", [2, 5]), _data("b", [2, 3])
        av = RNG.randn(2, 5).astype(np.float32)
        bv = RNG.randn(2, 3).astype(np.float32)
        got, = _run([v2l.conv_shift(a, b)], {"a": av, "b": bv})
        want = np.zeros_like(av)
        half = 1
        for n in range(2):
            for i in range(5):
                for j in range(3):
                    want[n, i] += av[n, (i + j - half) % 5] * bv[n, j]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_row_conv_shapes_and_params(self):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32",
                              lod_level=1)
        out = v2l.row_conv(x, context_len=3)
        params = [tuple(v.shape) for v in
                  fluid.default_startup_program().global_block()
                  .vars.values() if getattr(v, "persistable", False)]
        assert (3, 6) in params, params   # [context_len, D] exactly

    def test_get_output(self):
        assert v2l.get_output(("h", "c"), 1) == "c"
        assert v2l.get_output(("h", "c")) == "h"
        assert v2l.get_output("only") == "only"

    def test_cross_entropy_with_selfnorm(self):
        p = _data("p", [3, 4])
        lab = _data("lab", [3, 1], dtype="int64")
        probs = np.full((3, 4), 0.25, np.float32) * np.array(
            [[2.0], [1.0], [0.5]], np.float32)   # rows sum to 2, 1, .5
        labs = np.array([[0], [1], [2]], np.int64)
        got, = _run([v2l.cross_entropy_with_selfnorm(
            p, lab, softmax_selfnorm_alpha=0.5)], {"p": probs, "lab": labs})
        ce = -np.log(probs[np.arange(3), labs.ravel()])
        z = probs.sum(1)
        want = (ce + 0.5 * np.log(z) ** 2).mean()
        np.testing.assert_allclose(float(got.ravel()[0]), want, rtol=1e-4)

    def test_lambda_cost_prefers_better_ranking(self):
        """The LambdaRank cost must be lower when predicted scores agree
        with the relevance ordering than when they invert it."""
        pred = _data("pred", [2, 6])
        rel = _data("rel", [2, 6])
        cost = v2l.lambda_cost(pred, rel, NDCG_num=4)
        rel_v = np.tile(np.array([3, 2, 1, 0, 0, 0], np.float32), (2, 1))
        good = np.tile(np.linspace(3, -2, 6).astype(np.float32), (2, 1))
        bad = good[:, ::-1].copy()
        c_good, = _run([cost], {"pred": good, "rel": rel_v})
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            pred2 = _data("pred", [2, 6])
            rel2 = _data("rel", [2, 6])
            cost2 = v2l.lambda_cost(pred2, rel2, NDCG_num=4)
            c_bad, = _run([cost2], {"pred": bad, "rel": rel_v})
        assert float(c_good.ravel()[0]) < float(c_bad.ravel()[0])
        assert np.isfinite(c_good).all() and np.isfinite(c_bad).all()

    def test_seq_slice(self):
        from paddle_tpu.executor import LoDTensor
        x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                              lod_level=1)
        off = _data("off", [2, 1], dtype="int64")
        end = _data("end", [2, 1], dtype="int64")
        out = v2l.seq_slice(x, off, end)        # ends are END positions
        rows = np.arange(14, dtype=np.float32).reshape(7, 2)
        feed = {"x": LoDTensor(rows, [[0, 3, 7]]),
                "off": np.array([[1], [0]], np.int64),
                "end": np.array([[3], [2]], np.int64)}
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.default_startup_program())
            got, = exe.run(feed=feed, fetch_list=[out])
        want = np.concatenate([rows[1:3], rows[3:5]])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    def test_kmax_seq_score(self):
        from paddle_tpu.executor import LoDTensor
        x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                              lod_level=1)
        out = v2l.kmax_seq_score(x, beam_size=2)
        scores = np.array([[0.1], [0.9], [0.5],      # seq 1
                           [0.7], [0.2], [0.4], [0.8]],  # seq 2
                          np.float32)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.default_startup_program())
            got, = exe.run(feed={"x": LoDTensor(scores, [[0, 3, 7]])},
                           fetch_list=[out])
        got = np.asarray(got)
        assert got.shape[-1] == 2
        assert list(got[0]) == [1, 2]          # 0.9, 0.5
        assert list(got[1]) == [3, 0]          # 0.8, 0.7


class TestChannelExports:
    def test_fluid_surface(self):
        ch = fluid.make_channel(capacity=1)
        fluid.channel_send(ch, 5)
        assert fluid.channel_recv(ch) == (5, True)
        fluid.channel_close(ch)
        assert fluid.channel_recv(ch) == (None, False)
        assert callable(fluid.Go) and fluid.Select is not None
